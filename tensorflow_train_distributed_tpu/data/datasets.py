"""Synthetic dataset sources for every reference model config.

The reference ships tf.data builders for MNIST / ImageNet / BERT MLM /
WMT en-de (SURVEY.md §2.1).  This environment has no network and no stored
corpora, so each family gets a *deterministic procedural source*: records are
generated from a per-index PRNG (reproducible, O(1) storage, arbitrarily
large) with enough learnable structure that convergence tests are meaningful
— the role tf.data's in-repo toy datasets played for the reference's smoke
tests.  Real-data ingestion plugs in behind the same ``RandomAccessSource``
protocol.
"""

from __future__ import annotations

import numpy as np


def _rng(seed: int, idx: int) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence([seed, idx]))


class SyntheticMNIST:
    """28×28×1 digit-like images; label = which quadrant pattern is lit.

    Learnable by LeNet in a few dozen steps — the convergence canary for the
    reference's MNIST MirroredStrategy smoke config.
    """

    def __init__(self, num_examples: int = 60_000, num_classes: int = 10,
                 seed: int = 17):
        self.n, self.num_classes, self.seed = num_examples, num_classes, seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = _rng(self.seed, idx)
        label = idx % self.num_classes
        img = rng.normal(0.1, 0.1, (28, 28, 1)).astype(np.float32)
        # Class-dependent bright stripe: row band at label-th position.
        r0 = 2 + label * 2
        img[r0 : r0 + 3, 4:24, 0] += 1.0
        return {"image": np.clip(img, 0, 1), "label": np.int32(label)}


class SyntheticBlobs:
    """Linearly-separable gaussian blobs — fastest convergence unit fixture."""

    def __init__(self, num_examples: int = 4096, dim: int = 16,
                 num_classes: int = 4, seed: int = 3):
        self.n, self.dim, self.num_classes, self.seed = (
            num_examples, dim, num_classes, seed)
        centers_rng = np.random.default_rng(seed)
        self.centers = centers_rng.normal(0, 3.0, (num_classes, dim)).astype(
            np.float32)

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = _rng(self.seed, idx)
        label = idx % self.num_classes
        x = self.centers[label] + rng.normal(0, 0.5, self.dim).astype(np.float32)
        return {"x": x.astype(np.float32), "label": np.int32(label)}


class SyntheticImageNet:
    """224×224×3 images with class-dependent channel statistics (ResNet-50)."""

    def __init__(self, num_examples: int = 1_281_167, num_classes: int = 1000,
                 image_size: int = 224, seed: int = 29,
                 space_to_depth: bool = False):
        self.n, self.num_classes, self.size, self.seed = (
            num_examples, num_classes, image_size, seed)
        # Host-side 2x2 space-to-depth (models.resnet.space_to_depth): the
        # MXU-friendly input layout for the s2d stem, applied before
        # transfer so the device never sees the 3-channel tensor.
        self.space_to_depth = space_to_depth

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = _rng(self.seed, idx)
        label = idx % self.num_classes
        img = rng.normal(0, 1, (self.size, self.size, 3)).astype(np.float32)
        # Class signature: low-frequency pattern seeded by the label only.
        sig = np.random.default_rng(self.seed * 7919 + label)
        basis = sig.normal(0, 1, (8, 8, 3)).astype(np.float32)
        rep = -(-self.size // 8)  # ceil; crop handles non-multiple-of-8 sizes
        upsampled = np.repeat(np.repeat(basis, rep, axis=0), rep, axis=1)
        img += upsampled[: self.size, : self.size]
        if self.space_to_depth:
            s = self.size // 2
            img = (img.reshape(s, 2, s, 2, 3).transpose(0, 2, 1, 3, 4)
                   .reshape(s, s, 12))
        return {"image": img, "label": np.int32(label)}


class SyntheticLM:
    """Causal-LM token streams from a learnable affine recurrence.

    ``t[i+1] = (a*t[i] + b) mod vocab`` with (a, b) drawn per sequence — a
    next-token structure a transformer learns quickly, for Llama SFT and
    decoder throughput/convergence runs.
    """

    def __init__(self, num_examples: int = 100_000, seq_len: int = 512,
                 vocab_size: int = 32_000, seed: int = 41):
        self.n, self.seq_len, self.vocab, self.seed = (
            num_examples, seq_len, vocab_size, seed)

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = _rng(self.seed, idx)
        a = int(rng.integers(2, 64))
        b = int(rng.integers(0, self.vocab))
        t0 = int(rng.integers(0, self.vocab))
        toks = np.empty(self.seq_len + 1, np.int32)
        toks[0] = t0
        for i in range(self.seq_len):
            toks[i + 1] = (a * toks[i] + b) % self.vocab
        return {"tokens": toks[:-1], "targets": toks[1:]}


class SyntheticMLM:
    """BERT-style masked-LM records: tokens, 15% masked, target = original.

    Mirrors the reference BERT-base MLM pretrain config's input contract
    (input ids + masked positions + labels).
    """

    MASK_ID = 1

    def __init__(self, num_examples: int = 100_000, seq_len: int = 128,
                 vocab_size: int = 30_522, mask_frac: float = 0.15,
                 seed: int = 53):
        self.n, self.seq_len, self.vocab, self.mask_frac, self.seed = (
            num_examples, seq_len, vocab_size, mask_frac, seed)

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = _rng(self.seed, idx)
        # Learnable structure: palindromic halves, so masked tokens are
        # recoverable from context.
        half = rng.integers(2, self.vocab, self.seq_len // 2).astype(np.int32)
        tokens = np.concatenate([half, half[::-1]])
        n_mask = max(1, int(self.seq_len * self.mask_frac))
        pos = rng.choice(self.seq_len, n_mask, replace=False)
        inputs = tokens.copy()
        inputs[pos] = self.MASK_ID
        weights = np.zeros(self.seq_len, np.float32)
        weights[pos] = 1.0
        return {
            "input_ids": inputs,
            "labels": tokens,
            "mask_weights": weights,
        }


class SyntheticWMT:
    """Seq2seq pairs: target = source reversed with a fixed vocab rotation.

    Stands in for WMT en-de in the Transformer-big config; an encoder-decoder
    learns the copy/reverse/rotate mapping quickly.
    """

    BOS = 1
    EOS = 2

    def __init__(self, num_examples: int = 100_000, seq_len: int = 64,
                 vocab_size: int = 32_000, seed: int = 61):
        self.n, self.seq_len, self.vocab, self.seed = (
            num_examples, seq_len, vocab_size, seed)

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = _rng(self.seed, idx)
        src = rng.integers(3, self.vocab, self.seq_len - 1).astype(np.int32)
        tgt_core = ((src[::-1] + 7) % self.vocab).astype(np.int32)
        tgt_core[tgt_core < 3] += 3
        src_full = np.concatenate([src, [self.EOS]]).astype(np.int32)
        tgt_in = np.concatenate([[self.BOS], tgt_core]).astype(np.int32)
        tgt_out = np.concatenate([tgt_core, [self.EOS]]).astype(np.int32)
        return {"inputs": src_full, "targets_in": tgt_in,
                "targets_out": tgt_out}


class SliceSource:
    """Contiguous ``[start, stop)`` view of another source.

    The building block for held-out train/validation splits (Keras
    ``validation_split`` analog): both views share the underlying records
    with no copying, and each is a full ``RandomAccessSource``.
    """

    def __init__(self, source, start: int, stop: int):
        n = len(source)
        if not (0 <= start <= stop <= n):
            raise ValueError(
                f"invalid slice [{start}, {stop}) of a {n}-record source")
        self.source, self.start, self.stop = source, start, stop

    def __len__(self) -> int:
        return self.stop - self.start

    def __getitem__(self, idx: int):
        return self.get_record(idx, 0)

    def get_record(self, idx: int, epoch: int = 0):
        """Indexed fetch with the epoch threaded through the view —
        ``--eval-split`` wrapping must not freeze per-epoch augmentation
        (``pipeline.fetch_record`` semantics)."""
        if idx < 0 or idx >= len(self):
            raise IndexError(idx)
        from tensorflow_train_distributed_tpu.data.pipeline import (
            fetch_record,
        )

        return fetch_record(self.source, self.start + idx, epoch)

    @property
    def epoch_aware(self) -> bool:
        return getattr(self.source, "epoch_aware", False)


def train_val_split(source, val_fraction: float, *, min_val: int = 1,
                    min_train: int = 1):
    """Split a source into (train, holdout-tail) views.

    The tail — never the head — is held out so the training prefix is a
    stable function of the source regardless of the fraction.  ``min_val``
    and ``min_train`` (typically both the global batch size) guarantee each
    side can fill at least one batch — a split that can't is a config
    error, not a silent empty loader.
    """
    if not 0.0 < val_fraction < 1.0:
        raise ValueError(f"val_fraction must be in (0, 1), got {val_fraction}")
    n = len(source)
    n_val = max(int(n * val_fraction), min_val)
    cut = n - n_val
    if cut < min_train:
        raise ValueError(
            f"validation split of {n_val} leaves {max(cut, 0)} training "
            f"records < required {min_train} (source has {n}); shrink "
            "--eval-split or the batch size")
    return SliceSource(source, 0, cut), SliceSource(source, cut, n)


def _array_dir(root: str, transform=None):
    """On-disk mmap corpus (``filesource.write_shards`` layout)."""
    from tensorflow_train_distributed_tpu.data.filesource import open_sharded

    return open_sharded(root, transform=transform)


def _tfrecord_dir(root: str, transform=None, on_corrupt: str = "raise"):
    """Directory of ``*.tfrecord`` files + ``features.json`` sidecar."""
    from tensorflow_train_distributed_tpu.data.tfrecord import (
        open_tfrecord_dir,
    )

    return open_tfrecord_dir(root, transform=transform,
                             on_corrupt=on_corrupt)


_REGISTRY = {
    "mnist": SyntheticMNIST,
    "blobs": SyntheticBlobs,
    "imagenet": SyntheticImageNet,
    "lm": SyntheticLM,
    "mlm": SyntheticMLM,
    "wmt": SyntheticWMT,
    "array_dir": _array_dir,
    "tfrecord_dir": _tfrecord_dir,
}


def get_dataset(name: str, **kwargs):
    if name not in _REGISTRY:
        raise ValueError(f"Unknown dataset {name!r}; available: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)
