"""Always-on flight recorder: spans/instants in a bounded ring buffer.

The forensic layer the metrics scrape surface is not: ``/metrics``
aggregates (how many, how slow on average) — this module records the
TIMELINE (what happened to request X, why was step N slow), so
incidents on the overlap/interleave schedulers can be reconstructed
after the fact instead of reproduced under a profiler.  It is ON by
default and designed to stay on in production:

- recording is one lock-guarded ``deque.append`` of a small tuple —
  no I/O, no serialization, no allocation beyond the tuple and its
  attrs dict (measured ≤ 2 % serving tok/s against the kill switch;
  ``tools/bench_serving.py --trace-ab`` is the committed A/B);
- the buffer is a bounded ring (``TTD_TRACE_CAPACITY`` events, default
  65536): old events fall off the back, memory is O(capacity) forever;
- ``TTD_NO_TRACE=1`` is the kill switch: ``span()`` degrades to a
  shared no-op context manager and ``instant()`` to one dict lookup —
  an env flip, no redeploy (the ``TTD_NO_OVERLAP`` contract).
- ``TTD_TRACE_SPOOL=<dir>`` (off by default) adds the crash-durable
  layer: a flusher thread mirrors the ring into size-capped rotating
  JSONL segments (``TTD_TRACE_SPOOL_BYTES``, default 64 MiB/process),
  fsync-batched off the hot path, so the last seconds before a SIGKILL
  survive for ``tools/trace_report.py --post-mortem``.

Event model (exported as Chrome trace-event JSON, loadable in Perfetto
or ``chrome://tracing``):

- ``span(name, **attrs)`` — a context manager recording ONE complete
  event (``ph="X"``) at exit with monotonic start + duration.
  Recording at exit means the ring never holds an unbalanced begin.
- ``instant(name, **attrs)`` — a point event (``ph="i"``).
- timestamps are ``time.monotonic()`` (immune to wall-clock steps;
  the export carries a wall-clock anchor for cross-run alignment),
  ``tid`` is the recording thread's ident, ``pid`` the process.

Attrs are the correlation layer: the gateway driver tags request
lifecycle events with the ``request_id`` it minted at admission plus
the engine's ``rid`` once a slot is granted, the engine tags its
prefill/decode/retire events with ``rid``, and
``request_timeline()`` joins the two — the ``/v1/requests/<id>``
endpoint and ``tools/trace_report.py`` are its consumers.  Keep attr
values JSON-scalar (str/int/float/bool): the export serializes them
verbatim.

The compile-discipline sanitizer (``runtime.lint.compilecheck``,
``TTD_COMPILECHECK=1``) records a ``compile/<site>`` span around every
dispatch that compiles a new signature at an instrumented jit site —
compile time shows up in the same timeline as the decode/prefill spans
it stalls, and ``tools/trace_report.py`` folds the spans into a
per-site compilation table.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    concurrency_guarded,
    locks_held,
    thread_role,
)

_KILL_ENV = "TTD_NO_TRACE"
_CAPACITY_ENV = "TTD_TRACE_CAPACITY"
DEFAULT_CAPACITY = 65536

# -- crash-durable spool knobs --------------------------------------------
# ``TTD_TRACE_SPOOL=<dir>`` arms a per-process rotating JSONL spool: a
# flusher thread drains the ring through ``events_after`` every
# ``_SPOOL_FLUSH_S`` (write+flush per batch, fsync on the
# ``_SPOOL_FSYNC_S`` clock), so the recording hot path stays a
# deque.append and the disk sees the timeline at most one
# flush interval behind the crash.  Off by default — the ring alone is
# the production default; the spool is the post-mortem opt-in.
_SPOOL_ENV = "TTD_TRACE_SPOOL"
_SPOOL_BYTES_ENV = "TTD_TRACE_SPOOL_BYTES"
DEFAULT_SPOOL_BYTES = 64 << 20
_SPOOL_FLUSH_S = 0.25
#: Segments rotate at cap/4 (floor 1 MiB) and the oldest own segment is
#: unlinked once the per-process total would exceed the cap — disk use
#: is O(cap) forever, like the ring is O(capacity).
_SPOOL_MIN_SEGMENT = 1 << 20
#: Events per ``{"b": [...]}`` spool line: large enough that the batch
#: json.dumps amortizes (one C-level call per ~512 events, not one per
#: event), small enough that a line stays ~100 KiB and segment caps
#: are enforced at line granularity.
_SPOOL_BATCH_EVENTS = 512
#: fsync cadence.  Every batch is write()+flush()ed — that alone
#: survives PROCESS death (the post-mortem case: the kernel still owns
#: the pages when a worker is SIGKILLed); fsync only adds machine-
#: death durability and costs milliseconds on ext4, so it runs on a
#: clock instead of per batch.  Rotation and the final drain/SIGTERM
#: flush always fsync.
_SPOOL_FSYNC_S = 2.0

# Event tuple layout (kept flat — one small allocation per event):
# (name, ph, t0_monotonic_s, dur_s, tid, attrs_dict_or_None)


# The kill check runs per event on serving's per-chunk path, and
# ``os.environ.get`` costs ~1 us (encode + mapping indirection) vs
# ~0.14 us for the raw ``_data`` dict CPython keeps underneath (posix:
# fsencoded-bytes keys, kept in sync by __setitem__/__delitem__ — so
# monkeypatch.setenv flips it live too).  Fall back to the public API
# where the private layout differs.


def make_env_flag_reader(env_name: str):
    """A ``() -> bool`` truthiness reader for one env flag, using the
    ``os.environ._data`` fast path when the layout allows — THE shared
    implementation of every per-event/per-dispatch live kill switch
    (``TTD_NO_TRACE`` here, ``TTD_NO_COMPILECHECK`` in
    runtime.lint.compilecheck), so the subtle layout probe lives
    once."""
    try:
        env_data = os.environ._data
        key = os.fsencode(env_name)
        # Layout probe: the fast path needs bytes keys (posix).  A
        # str-keyed _data (Windows) would make .get() return None
        # forever — silently disabling the kill switch — so check the
        # key type, not just that .get() doesn't raise.
        if not isinstance(next(iter(env_data)), bytes):
            raise TypeError("os.environ._data keys are not bytes")

        def read() -> bool:
            v = env_data.get(key)
            return v is not None and v not in (b"", b"0")
    except (AttributeError, TypeError, StopIteration):  # pragma: no cover
        def read() -> bool:
            return os.environ.get(env_name, "0") not in ("", "0")
    return read


#: ``TTD_NO_TRACE=1`` disables recording process-wide (re-read per
#: event, so a test or an operator shell can flip it live).
trace_killed = make_env_flag_reader(_KILL_ENV)


class _Span:
    """One recording span: appends a single complete event at exit."""

    __slots__ = ("_rec", "_name", "_attrs", "t0")

    def __init__(self, rec: "Recorder", name: str, attrs: Optional[dict]):
        self._rec = rec
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_Span":
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.monotonic()
        self._rec._append(self._name, "X", self.t0, t1 - self.t0,
                          self._attrs)
        return False


class _NullSpan:
    """The kill-switch span: no clock reads, no append, one shared
    instance."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()

# Per-thread default attrs (module-level: one store shared by every
# recorder).  A replica's driver thread sets {"replica": k} once and
# every engine/driver event it records carries the id — the
# correlation key multi-replica forensics needs, with zero per-call
# plumbing through the engine.
_TLS = threading.local()


def set_thread_attrs(**attrs) -> None:
    """Replace THIS thread's default event attrs (merged under any
    per-event attrs at record time; call with no kwargs to clear).
    The pool's driver and pump threads tag themselves with
    ``replica=k`` so engine-side events — which know nothing about
    replicas — land on the right timeline."""
    _TLS.attrs = dict(attrs) if attrs else None


def get_thread_attrs() -> Optional[dict]:
    return getattr(_TLS, "attrs", None)


@concurrency_guarded
class Recorder:
    """Lock-cheap bounded ring buffer of trace events.

    Threads append concurrently (driver loop, HTTP handlers, trainer
    host thread); readers snapshot under the same lock.  The lock is
    held for one ``deque.append`` / one ``list()`` copy — never across
    user code.
    """

    # Every thread role appends; every access locks (ttd-lint's
    # concurrency checker + TTD_LOCKCHECK=1 enforce it stays so).
    # The spool state dict is shared by the flusher thread and any
    # thread calling flush_spool()/stop_spool() (worker drain, tests).
    _GUARDED_BY = {"_buf": ("_lock",), "_seq": ("_lock",),
                   "_spool": ("_spool_lock",)}

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.pid = os.getpid()
        self._buf: deque = deque(maxlen=capacity)
        # Total events ever appended — the cursor feed for
        # ``events_after`` (a subprocess worker's event-relay loop
        # ships only what it has not shipped yet; a deque index would
        # shift as the ring drops old events, a running sequence does
        # not).
        self._seq = 0
        self._lock = threading.Lock()
        # Wall-clock anchor: wall time at monotonic ``_anchor_mono`` —
        # lets offline tooling place the monotonic timeline in real
        # time (e.g. against a supervisor journal's ``time.time()``).
        self._anchor_mono = time.monotonic()
        self._anchor_wall = time.time()
        # Crash-durable spool (None until armed).  Auto-arms when
        # ``TTD_TRACE_SPOOL`` names a directory: subprocess workers
        # inherit the env, so one flag spools the whole fleet — each
        # process into its own pid-named segments.
        self._spool: Optional[dict] = None
        self._spool_lock = threading.Lock()
        self._spool_stop = threading.Event()
        if os.environ.get(_SPOOL_ENV, ""):
            try:
                self.start_spool()
            except OSError:
                # An unwritable spool dir must not take the process —
                # the ring (the production surface) still works.
                pass

    @property
    def enabled(self) -> bool:
        return not trace_killed()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def _append(self, name: str, ph: str, t0: float, dur: float,
                attrs: Optional[dict]) -> None:
        base = getattr(_TLS, "attrs", None)
        if base:
            # Per-event attrs win over the thread defaults.
            attrs = {**base, **(attrs or {})}
        ev = (name, ph, t0, dur, threading.get_ident(), attrs or None)
        with self._lock:
            self._buf.append(ev)
            self._seq += 1

    # -- recording api ---------------------------------------------------

    def span(self, name: str, **attrs):
        """Context manager timing its block into one complete event
        (``ph="X"``); a no-op singleton under the kill switch.

        Repeated spans of one name within a step are the sub-span
        convention (no nesting needed): the bucketed-overlap trainer
        emits one ``train/grad_comm`` / ``train/optimizer_apply`` span
        PER BUCKET, tagged ``bucket=<i>, buckets=<K>`` in attrs, plus a
        single ``train/step_barrier`` span at the only host-blocking
        point — trace_report groups same-name spans per step and breaks
        them out per bucket when the ``bucket`` attr is present."""
        if trace_killed():
            return _NULL_SPAN
        return _Span(self, name, attrs or None)

    def instant(self, name: str, **attrs) -> None:
        """Record a point event (``ph="i"``)."""
        if trace_killed():
            return
        self._append(name, "i", time.monotonic(), 0.0, attrs or None)

    def record_at(self, name: str, ph: str, t0: float, dur: float = 0.0,
                  attrs: Optional[dict] = None) -> None:
        """Record one event with a CALLER-supplied timestamp — the
        relay path for events that happened in another process (a
        subprocess replica ships its recorder's events in stats frames;
        the parent re-records them mapped into its own monotonic
        domain so ``request_timeline`` joins both lives of a
        failed-over request).  Honors the kill switch like every
        recording entry point."""
        if trace_killed():
            return
        self._append(name, ph if ph in ("X", "i") else "i", t0,
                     dur if ph == "X" else 0.0, dict(attrs or {}) or None)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    # -- query / export --------------------------------------------------

    def events(self, last_s: Optional[float] = None) -> list:
        """Snapshot of the ring (oldest first), optionally only events
        whose END falls inside the trailing ``last_s`` seconds."""
        with self._lock:
            items = list(self._buf)
        if last_s is not None:
            cutoff = time.monotonic() - last_s
            items = [e for e in items if e[2] + e[3] >= cutoff]
        return items

    def events_after(self, cursor: int) -> tuple:
        """``(new_cursor, events)``: every event appended since
        ``cursor`` (a value previously returned here; 0 = everything
        still in the ring).  The cursor is the recorder's running
        append sequence, so it stays exact while the bounded ring
        drops old events — events that fell off the back before being
        read are simply gone (the ring's contract), never re-delivered
        and never double-delivered.  The subprocess worker's stats
        loop is the consumer: each frame ships exactly the new tail."""
        with self._lock:
            seq = self._seq
            fresh = seq - int(cursor)
            if fresh <= 0:
                return seq, []
            n = len(self._buf)
            if fresh >= n:
                items = list(self._buf)
            else:
                # O(tail) copy, not O(capacity): the stats loop and
                # the spool flusher each poll a few times a second,
                # and list(deque) walks the whole ring every poll.
                rev = reversed(self._buf)
                items = [next(rev) for _ in range(fresh)]
                items.reverse()
        return seq, items

    def request_timeline(self, request_id: int) -> list:
        """Every event belonging to gateway request ``request_id``,
        sorted by start time: driver events tagged ``request_id``
        (from the LATEST admission of that id — ids restart per driver,
        forensics wants the most recent life) joined with engine events
        tagged with the ``rid`` each engine-submit recorded, scoped to
        [engine-submit, next engine-submit or retire] so a reused
        engine rid from another session cannot bleed in.  A replica
        pool's request has ONE ``request/pool_admitted`` anchor (which
        outranks the per-life ``request/admitted`` events — failover
        re-admits the same id on a survivor, and the timeline must
        show both lives plus the hop) and possibly several
        engine-submit segments, each additionally keyed on its
        ``replica`` attr so two replicas' identical engine rids never
        cross-join."""
        evs = self.events()
        admit_t = pool_t = solo_t = None
        for e in evs:               # latest admission wins, per kind
            a = e[5]
            if a is None or a.get("request_id") != request_id:
                continue
            if e[0] == "request/pool_admitted":
                pool_t = e[2]
            elif e[0] == "request/admitted":
                admit_t = e[2]
                # A per-life admission on a pool replica carries the
                # replica id; a STANDALONE driver's does not.  Only
                # the latter may outrank a pool anchor — a newer
                # single-driver request reusing the id (driver ids
                # restart per driver) must not join a stale pool
                # life's events, and vice versa a failover's per-life
                # re-admissions must never displace their own pool
                # anchor.
                if a.get("replica") is None:
                    solo_t = e[2]
        if pool_t is not None and (solo_t is None or pool_t > solo_t):
            admit_t = pool_t
        out = []
        segs: list = []           # [rid, replica, grant_t, hi] per life
        retire_t = None
        for e in evs:
            a = e[5]
            if (a is None or a.get("request_id") != request_id
                    or (admit_t is not None and e[2] < admit_t)):
                continue
            out.append(e)
            if e[0] == "request/engine_submit" and "rid" in a:
                if segs:        # previous life ends where this begins
                    segs[-1][3] = min(segs[-1][3], e[2])
                segs.append([a["rid"], a.get("replica"), e[2],
                             float("inf")])
            if e[0] == "request/retire":
                retire_t = e[2]
        if segs and retire_t is not None and retire_t >= segs[-1][2]:
            # hi is exact: the driver's retire follows every engine
            # event of the request (the harvest trim guard keeps a
            # retired rid from ever being tagged again).
            segs[-1][3] = min(segs[-1][3], retire_t)
        for rid, replica, grant_t, hi in segs:
            # lo is padded: the engine's own queued instant fires just
            # BEFORE the driver records the engine-submit join anchor.
            lo = grant_t - 1e-3
            for e in evs:
                a = e[5]
                if (a is not None and "request_id" not in a
                        and a.get("rid") == rid and lo <= e[2] <= hi
                        and (replica is None
                             or a.get("replica") in (None, replica))):
                    out.append(e)
        out.sort(key=lambda e: e[2])
        return out

    def export_chrome_trace(self, last_s: Optional[float] = None) -> dict:
        """Chrome trace-event JSON (the ``traceEvents`` array form):
        every event carries ``name``/``ph``/``ts``/``pid``/``tid``
        (ts/dur in microseconds), spans are complete events (``X``) so
        the trace is balanced by construction — load the dict's JSON in
        Perfetto or ``chrome://tracing`` as-is."""
        trace_events = []
        for name, ph, t0, dur, tid, attrs in self.events(last_s):
            ev = {
                "name": name,
                "cat": name.split("/", 1)[0],
                "ph": ph,
                "ts": round(t0 * 1e6, 3),
                "pid": self.pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = round(dur * 1e6, 3)
            elif ph == "i":
                ev["s"] = "t"          # thread-scoped instant
            if attrs:
                ev["args"] = dict(attrs)
            trace_events.append(ev)
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "pid": self.pid,
                "capacity": self.capacity,
                "clock": "monotonic_us",
                "wall_anchor_s": self._anchor_wall,
                "mono_anchor_us": round(self._anchor_mono * 1e6, 3),
                "killed": trace_killed(),
            },
        }

    def save(self, path: str, last_s: Optional[float] = None) -> None:
        with open(path, "w") as f:
            json.dump(self.export_chrome_trace(last_s), f)

    # -- crash-durable spool ---------------------------------------------
    #
    # The ring answers "what happened" only while the process is alive
    # to be asked.  The spool is the same timeline made to survive the
    # asker: ``spool-<pid>-<n>.jsonl`` segments, each opened with a
    # header line carrying the pid and the wall/monotonic anchor pair
    # (so offline tooling can place a dead process's monotonic
    # timestamps in real time), then one compact ``{"b": [...]}``
    # batch line per flush — event arrays in ring-tuple order.  A
    # flusher thread drains ``events_after`` every flush interval
    # (write+flush per batch, fsync on a clock — see
    # ``_SPOOL_FSYNC_S``); if the ring laps the flusher, an honest
    # ``{"dropped": n}`` line marks the gap.
    # ``tools/trace_report.py --post-mortem`` is the consumer.

    def start_spool(self, directory: Optional[str] = None) -> Optional[str]:
        """Arm the crash-durable spool into ``directory`` (default: the
        ``TTD_TRACE_SPOOL`` env var; no-op returning None when unset).
        Idempotent — a second call returns the armed directory."""
        directory = directory or os.environ.get(_SPOOL_ENV, "")
        if not directory:
            return None
        with self._spool_lock:
            if self._spool is not None:
                return self._spool["dir"]
            os.makedirs(directory, exist_ok=True)
            raw = os.environ.get(_SPOOL_BYTES_ENV, "")
            cap = int(raw) if raw else DEFAULT_SPOOL_BYTES
            self._spool = {
                "dir": directory,
                "cap": max(cap, 2 * _SPOOL_MIN_SEGMENT),
                "seg_cap": max(cap // 4, _SPOOL_MIN_SEGMENT),
                "cursor": 0,      # events_after sequence already spooled
                "seg": 0,
                "fh": None,
                "path": "",
                "written": 0,     # bytes in the open segment
                "segments": [],   # [(path, bytes)] closed, oldest first
                "dropped": 0,
                "last_fsync": time.monotonic(),
            }
            self._spool_open_segment()
        self._spool_stop.clear()
        t = threading.Thread(target=self._spool_loop, name="trace-spool",
                             daemon=True)
        t.start()
        return directory

    @locks_held("_spool_lock")
    def _spool_open_segment(self) -> None:
        """Rotate to a fresh segment, then unlink our own oldest closed
        segments until the per-process total fits the byte cap."""
        st = self._spool
        fh = st["fh"]
        if fh is not None:
            try:
                fh.flush()
                os.fsync(fh.fileno())
                st["last_fsync"] = time.monotonic()
                fh.close()
            except OSError:
                pass
            st["segments"].append((st["path"], st["written"]))
        st["seg"] += 1
        path = os.path.join(
            st["dir"], f"spool-{self.pid}-{st['seg']:04d}.jsonl")
        fh = open(path, "wb")
        header = json.dumps({
            "spool": 1,
            "pid": self.pid,
            "segment": st["seg"],
            "capacity": self.capacity,
            "wall_anchor_s": self._anchor_wall,
            "mono_anchor_s": self._anchor_mono,
            "open_wall_s": time.time(),
            "open_mono_s": time.monotonic(),
        }, separators=(",", ":")).encode() + b"\n"
        fh.write(header)
        st["fh"], st["path"], st["written"] = fh, path, len(header)
        total = st["written"] + sum(b for _, b in st["segments"])
        while st["segments"] and total > st["cap"]:
            old_path, old_bytes = st["segments"].pop(0)
            try:
                os.unlink(old_path)
            except OSError:
                pass
            total -= old_bytes

    @locks_held("_spool_lock")
    def _spool_flush_once(self, force_fsync: bool = False) -> int:
        """Drain the ring's new tail to disk (write+flush per batch,
        fsync on the ``_SPOOL_FSYNC_S`` clock or when forced); returns
        the number of events written.  An OSError (full disk, revoked
        dir) disables the spool but must never take the process — the
        ring keeps working."""
        st = self._spool
        if st is None or st["fh"] is None:
            return 0
        cursor, evs = self.events_after(st["cursor"])
        fresh = cursor - st["cursor"]
        st["cursor"] = cursor
        if fresh <= 0:
            return 0
        chunks = []
        if fresh > len(evs):
            st["dropped"] += fresh - len(evs)
            chunks.append(json.dumps(
                {"dropped": fresh - len(evs),
                 "mono_s": round(time.monotonic(), 6)},
                separators=(",", ":")).encode() + b"\n")
        # One dumps call per ``{"b": [[...], ...]}`` batch line,
        # straight from the ring tuples: per-event dumps costs ~7 µs
        # an event and the flusher shares a core (and a GIL) with the
        # serving threads it is observing — on a small host that read
        # as tok/s overhead in the --trace-fleet-ab bench.  Batches
        # are sliced so one line stays line-sized and the segment cap
        # is enforced between slices, not after a megabyte write.  A
        # torn tail line loses at most one slice of one flush window
        # (~0.25 s) — the window an unflushed ring loses anyway.
        for lo in range(0, len(evs), _SPOOL_BATCH_EVENTS):
            chunks.append(json.dumps(
                {"b": evs[lo:lo + _SPOOL_BATCH_EVENTS]},
                separators=(",", ":"), default=str).encode() + b"\n")
        try:
            for data in chunks:
                if st["written"] >= st["seg_cap"]:
                    self._spool_open_segment()
                st["fh"].write(data)
                st["written"] += len(data)
            st["fh"].flush()
            now = time.monotonic()
            if force_fsync or now - st["last_fsync"] >= _SPOOL_FSYNC_S:
                os.fsync(st["fh"].fileno())
                st["last_fsync"] = now
        except OSError:
            try:
                st["fh"].close()
            except OSError:
                pass
            st["fh"] = None
        return len(evs)

    @thread_role("watchdog")
    def _spool_loop(self) -> None:
        while not self._spool_stop.wait(_SPOOL_FLUSH_S):
            with self._spool_lock:
                if self._spool is None or self._spool["fh"] is None:
                    return
                self._spool_flush_once()

    def flush_spool(self) -> int:
        """Synchronously drain the ring to the spool and fsync — the
        worker's final-flush hook on drain/SIGTERM, and the test seam.
        Returns events written (0 when the spool is not armed)."""
        with self._spool_lock:
            return self._spool_flush_once(force_fsync=True)

    def stop_spool(self) -> None:
        """Final flush, close the open segment, disarm."""
        self._spool_stop.set()
        with self._spool_lock:
            self._spool_flush_once(force_fsync=True)
            st = self._spool
            if st is not None and st["fh"] is not None:
                try:
                    st["fh"].flush()
                    os.fsync(st["fh"].fileno())
                    st["fh"].close()
                except OSError:
                    pass
                st["fh"] = None
            self._spool = None

    def spool_info(self) -> Optional[dict]:
        """Armed-spool status for health surfaces (None when off)."""
        with self._spool_lock:
            st = self._spool
            if st is None:
                return None
            return {
                "dir": st["dir"],
                "segment": st["seg"],
                "written_bytes": st["written"],
                "segments": len(st["segments"]) + 1,
                "dropped": st["dropped"],
                "active": st["fh"] is not None,
            }


# -- process-global recorder ---------------------------------------------

_cap = os.environ.get(_CAPACITY_ENV, "")
_RECORDER = Recorder(int(_cap) if _cap else DEFAULT_CAPACITY)
del _cap


def get_recorder() -> Recorder:
    return _RECORDER


def span(name: str, **attrs):
    """``with events.span("decode/harvest", rid=3): ...`` on the
    process-global recorder."""
    return _RECORDER.span(name, **attrs)


def instant(name: str, **attrs) -> None:
    _RECORDER.instant(name, **attrs)
