"""Self-healing training supervisor: run, classify the exit, relaunch.

The reference's fault-tolerance story assumes an external cluster
manager relaunches a preempted/crashed job after
``PreemptionCheckpointHandler`` saves (SURVEY.md §5.3) — the
save-and-stop half lives in ``runtime.preemption``; this module is the
bring-it-back half, so a single command survives a ``kill -9``, a
poisoned step, or a reclaimed VM without a Borg/K8s controller above
it.

Contract:

- the child is launched as a fresh process (``sys.executable -m
  tensorflow_train_distributed_tpu ...`` via the CLI, or any argv) with
  ``TTD_SUPERVISE_ATTEMPT=<n>`` exported — fault plans
  (``runtime.faults``) key one-shot faults off it, and tooling can log
  it;
- exit 0 → done;
- exit ``PREEMPTION_EXIT_CODE`` (143, ``runtime.preemption``) →
  *preemption*: the job checkpointed and stopped on purpose; relaunch
  immediately and do NOT consume the crash restart budget (a
  maintenance event is not a bug, and budgeting it would let routine
  preemptions exhaust the real crash protection);
- anything else (including death by signal: Popen returncode ``-N``) →
  *crash*: relaunch under exponential backoff until ``max_restarts``
  crashes have been spent, then give up with the last exit code.

Recovery on relaunch is the CLI's existing auto-resume
(``--checkpoint-dir`` restores the latest step; crash-consistent
fallback in ``training.checkpoint`` quarantines a torn latest save and
falls back to the previous good one) — the supervisor deliberately
knows nothing about checkpoints.

Every attempt appends one JSON line to the journal (audit trail +
test surface): ``{"event": "exit", "attempt", "rc", "class",
"duration_s", "backoff_s"}`` and a final ``{"event": "done"|"giveup"}``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Optional, Sequence

from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    thread_role,
)

from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.preemption import (
    PREEMPTION_EXIT_CODE,
)

logger = logging.getLogger(__name__)

ENV_ATTEMPT = "TTD_SUPERVISE_ATTEMPT"


def classify_exit(returncode: int) -> str:
    """``clean`` | ``preemption`` | ``crash`` from a child returncode."""
    if returncode == 0:
        return "clean"
    if returncode == PREEMPTION_EXIT_CODE:
        return "preemption"
    return "crash"


@dataclasses.dataclass
class SupervisorResult:
    returncode: int
    attempts: int
    crashes: int
    preemptions: int
    gave_up: bool


class TrainSupervisor:
    """Run ``argv`` as a child process until it exits clean, the crash
    budget is spent, or (optionally) preemptions stop being restartable.

    ``backoff_s`` doubles per *consecutive* crash (a clean stretch of
    preemptions resets nothing — only a successful exit ends the loop —
    but the exponent counts crashes, so preemption churn never inflates
    crash delays), capped at ``backoff_max_s``.  Preemption relaunches
    wait a flat ``backoff_s`` (no exponent — a maintenance event is not
    a bug, but zero delay would let a child that exits 143 at startup
    spin the loop unboundedly).

    The supervisor itself forwards SIGTERM/SIGINT to the live child and
    then stops relaunching (``handle_signals=True``, main thread only):
    a scheduler terminating the *supervisor* means the whole job should
    checkpoint and stop, not lose the relaunch loop out from under a
    training child mid-save.
    """

    def __init__(self, argv: Sequence[str], *,
                 max_restarts: int = 3,
                 backoff_s: float = 1.0,
                 backoff_max_s: float = 60.0,
                 restart_on_preemption: bool = True,
                 journal_path: Optional[str] = None,
                 env: Optional[dict] = None,
                 handle_signals: bool = True,
                 sleep=time.sleep):
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}")
        if backoff_s < 0:
            raise ValueError(f"backoff_s must be >= 0, got {backoff_s}")
        self.argv = list(argv)
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.restart_on_preemption = restart_on_preemption
        self.journal_path = journal_path
        self.env = env
        self.handle_signals = handle_signals
        self._sleep = sleep
        self._proc: Optional[subprocess.Popen] = None
        self._stop_signal: Optional[int] = None

    def _journal(self, record: dict) -> None:
        # Journal lines double as flight-recorder instants, so attempt
        # boundaries/relaunches land on the same timeline as the
        # trainer's step spans (runtime.events; tools/trace_report.py
        # renders both).
        events.instant(
            "supervisor/" + str(record.get("event", "event")),
            **{k: v for k, v in record.items()
               if k != "event" and isinstance(v, (str, int, float, bool))})
        if not self.journal_path:
            return
        os.makedirs(os.path.dirname(os.path.abspath(self.journal_path)),
                    exist_ok=True)
        with open(self.journal_path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def _launch(self, attempt: int) -> int:
        env = dict(os.environ if self.env is None else self.env)
        env[ENV_ATTEMPT] = str(attempt)
        logger.info("supervisor attempt %d: %s", attempt,
                    " ".join(self.argv))
        # No stdout/stderr capture: the child IS the training job; its
        # logs stream to the operator exactly as an unsupervised run's
        # would.
        self._proc = subprocess.Popen(self.argv, env=env)
        try:
            # PEP 475: a forwarded signal interrupts this wait, runs the
            # handler, and the wait resumes until the child exits.
            return self._proc.wait()
        finally:
            self._proc = None

    def _forward_signal(self, signum, frame) -> None:
        self._stop_signal = signum
        logger.warning(
            "supervisor: got signal %d; forwarding to the child and "
            "stopping the relaunch loop after it exits", signum)
        proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            except OSError:      # child raced to exit
                pass

    @thread_role("supervisor")
    def run(self) -> SupervisorResult:
        prev_handlers = {}
        if self.handle_signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev_handlers[sig] = signal.signal(
                        sig, self._forward_signal)
                except ValueError:      # not on the main thread
                    prev_handlers.clear()
                    break
        try:
            return self._run()
        finally:
            for sig, handler in prev_handlers.items():
                signal.signal(sig, handler)

    def _run(self) -> SupervisorResult:
        attempt = crashes = preemptions = 0
        while True:
            if self._stop_signal is not None:
                # The stop signal landed while NO child was live (during
                # a backoff sleep, or between exit and relaunch): there
                # was nothing to forward it to, so stop here — launching
                # a fresh child against the scheduler's kill would run
                # the whole remaining job.
                logger.warning(
                    "supervisor: stop signal %d pending before relaunch; "
                    "not launching attempt %d", self._stop_signal, attempt)
                self._journal({"event": "stopped",
                               "signal": self._stop_signal,
                               "attempts": attempt, "crashes": crashes,
                               "preemptions": preemptions,
                               "rc": 128 + self._stop_signal})
                return SupervisorResult(128 + self._stop_signal, attempt,
                                        crashes, preemptions,
                                        gave_up=False)
            t0 = time.monotonic()
            rc = self._launch(attempt)
            duration = time.monotonic() - t0
            klass = classify_exit(rc)
            backoff = 0.0
            if klass == "crash":
                crashes += 1
                backoff = min(self.backoff_max_s,
                              self.backoff_s * 2 ** (crashes - 1))
            elif klass == "preemption":
                preemptions += 1
                # Flat base delay, no exponent: preemption relaunches
                # are free of the crash budget, so without it a child
                # exiting 143 right at startup would spin unboundedly.
                if self.restart_on_preemption:
                    backoff = self.backoff_s
            self._journal({"event": "exit", "attempt": attempt,
                           "rc": rc, "class": klass,
                           "duration_s": round(duration, 3),
                           "backoff_s": backoff, "time": time.time()})
            attempt += 1
            if klass != "clean" and self._stop_signal is not None:
                # The supervisor itself was told to stop: the child got
                # the forwarded signal (its 143 here means it saved and
                # stopped on purpose) — hand its code up, never relaunch
                # against the scheduler's will.
                self._journal({"event": "stopped",
                               "signal": self._stop_signal,
                               "attempts": attempt, "crashes": crashes,
                               "preemptions": preemptions, "rc": rc})
                return SupervisorResult(rc, attempt, crashes, preemptions,
                                        gave_up=False)
            if klass == "clean":
                logger.info("supervisor: clean exit after %d attempt(s)",
                            attempt)
                self._journal({"event": "done", "attempts": attempt,
                               "crashes": crashes,
                               "preemptions": preemptions})
                return SupervisorResult(0, attempt, crashes, preemptions,
                                        gave_up=False)
            if klass == "preemption":
                if not self.restart_on_preemption:
                    logger.warning(
                        "supervisor: preemption exit %d; restart "
                        "disabled — handing rc to the caller", rc)
                    self._journal({"event": "done", "attempts": attempt,
                                   "crashes": crashes,
                                   "preemptions": preemptions})
                    return SupervisorResult(rc, attempt, crashes,
                                            preemptions, gave_up=False)
                logger.warning(
                    "supervisor: preemption exit (rc=%d); relaunching "
                    "in %.2fs (crash budget untouched: %d/%d)", rc,
                    backoff, crashes, self.max_restarts)
                if backoff:
                    self._sleep(backoff)
                continue
            # crash
            if crashes > self.max_restarts:
                logger.error(
                    "supervisor: crash rc=%d exhausted the restart "
                    "budget (%d crashes > %d restarts); giving up",
                    rc, crashes, self.max_restarts)
                self._journal({"event": "giveup", "attempts": attempt,
                               "crashes": crashes,
                               "preemptions": preemptions, "rc": rc})
                return SupervisorResult(rc, attempt, crashes, preemptions,
                                        gave_up=True)
            logger.warning(
                "supervisor: crash rc=%d (%s); relaunching in %.2fs "
                "(crash %d/%d)", rc,
                f"signal {-rc}" if rc < 0 else "exit",
                backoff, crashes, self.max_restarts)
            if backoff:
                self._sleep(backoff)


SUPERVISOR_FLAGS = {
    # flag -> takes a value?  (the strip list for child argv rebuild)
    "--supervise": False,
    "--max-restarts": True,
    "--restart-backoff": True,
    "--restart-backoff-max": True,
    "--no-restart-on-preemption": False,
    "--supervisor-journal": True,
}


def strip_supervisor_flags(argv: Sequence[str]) -> list:
    """Remove supervisor-only flags from a CLI argv, producing the
    child's argv tail (the supervisor must not recurse)."""
    out = []
    i = 0
    args = list(argv)
    while i < len(args):
        a = args[i]
        flag = a.split("=", 1)[0]
        if flag in SUPERVISOR_FLAGS:
            if SUPERVISOR_FLAGS[flag] and "=" not in a:
                i += 1              # consume the separate value
            i += 1
            continue
        out.append(a)
        i += 1
    return out


def supervise_cli(argv: Sequence[str], args) -> int:
    """``launch.py --supervise`` entry: re-run this CLI (minus the
    supervisor flags) under a ``TrainSupervisor`` built from ``args``."""
    child = [sys.executable, "-m", "tensorflow_train_distributed_tpu",
             *strip_supervisor_flags(argv)]
    journal = args.supervisor_journal
    if journal is None and args.checkpoint_dir:
        journal = os.path.join(args.checkpoint_dir, "supervisor.jsonl")
    sup = TrainSupervisor(
        child,
        max_restarts=args.max_restarts,
        backoff_s=args.restart_backoff,
        backoff_max_s=args.restart_backoff_max,
        restart_on_preemption=not args.no_restart_on_preemption,
        journal_path=journal,
    )
    return sup.run().returncode
