"""Self-healing training supervisor: run, classify the exit, relaunch.

The reference's fault-tolerance story assumes an external cluster
manager relaunches a preempted/crashed job after
``PreemptionCheckpointHandler`` saves (SURVEY.md §5.3) — the
save-and-stop half lives in ``runtime.preemption``; this module is the
bring-it-back half, so a single command survives a ``kill -9``, a
poisoned step, or a reclaimed VM without a Borg/K8s controller above
it.

Contract:

- the child is launched as a fresh process (``sys.executable -m
  tensorflow_train_distributed_tpu ...`` via the CLI, or any argv) with
  ``TTD_SUPERVISE_ATTEMPT=<n>`` exported — fault plans
  (``runtime.faults``) key one-shot faults off it, and tooling can log
  it;
- exit 0 → done;
- exit ``PREEMPTION_EXIT_CODE`` (143, ``runtime.preemption``) →
  *preemption*: the job checkpointed and stopped on purpose; relaunch
  immediately and do NOT consume the crash restart budget (a
  maintenance event is not a bug, and budgeting it would let routine
  preemptions exhaust the real crash protection);
- exit ``DEVICE_LOSS_EXIT_CODE`` (113) → *device loss*: part of the
  mesh died (``runtime.faults.DeviceLost`` — injected or inferred from
  a runtime error); the child recorded the surviving device count in
  the elastic sidecar (``TTD_ELASTIC_STATE``) before exiting, and the
  supervisor relaunches onto the survivors by exporting
  ``TTD_ELASTIC_DEVICES=<M>`` — the relaunch restores the latest
  checkpoint RESHARDED onto the smaller mesh
  (``training.checkpoint``).  Free of the crash budget, like
  preemption: losing hardware is not a bug in the program.
  ``TTD_NO_ELASTIC=1`` (or ``elastic=False``) reverts to classifying
  it as a plain crash — no resize, budget consumed;
- anything else (including death by signal: Popen returncode ``-N``) →
  *crash*: relaunch under jittered exponential backoff until
  ``max_restarts`` crashes have been spent, then give up with the last
  exit code.  ``restart_window_s`` makes the accounting a ROLLING
  window instead of lifetime: only crashes inside the window count
  against the budget, so a correlated burst (a rack reboot taking
  several relaunches down at once) cannot permanently exhaust the
  protection a long healthy run still deserves.  The jitter
  (``backoff_jitter``, fraction of the delay) decorrelates relaunch
  stampedes when many supervised jobs crash on the same event.

Recovery on relaunch is the CLI's existing auto-resume
(``--checkpoint-dir`` restores the latest step; crash-consistent
fallback in ``training.checkpoint`` quarantines a torn latest save and
falls back to the previous good one) — the supervisor deliberately
knows nothing about checkpoints.

Every attempt appends one JSON line to the journal (audit trail +
test surface): ``{"event": "exit", "attempt", "rc", "class",
"duration_s", "backoff_s"}`` and a final ``{"event": "done"|"giveup"}``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional, Sequence

from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    thread_role,
)

from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.preemption import (
    PREEMPTION_EXIT_CODE,
)

logger = logging.getLogger(__name__)

ENV_ATTEMPT = "TTD_SUPERVISE_ATTEMPT"

# Device-loss exit-code contract (the elastic analog of
# PREEMPTION_EXIT_CODE): a child exiting with THIS code lost part of
# its device mesh (runtime.faults.DeviceLost — injected or inferred
# from a runtime error), wrote the surviving device count to the
# elastic sidecar, and wants to be relaunched onto the survivors.
# 113 carries no 128+signal meaning and collides with no conventional
# code; launch.py, the supervisor, and external schedulers share it.
DEVICE_LOSS_EXIT_CODE = 113

# Supervisor → child: where the child must record the surviving device
# count on a device loss (JSON: {"survivors": M, ...}).
ENV_ELASTIC_STATE = "TTD_ELASTIC_STATE"
# Supervisor → relaunched child: train on this many devices (the
# surviving set).  launch.py shrinks its virtual CPU platform or
# slices jax.devices() accordingly and lets the mesh preset re-resolve.
ENV_ELASTIC_DEVICES = "TTD_ELASTIC_DEVICES"
# Kill switch: classify device loss as a plain crash (no resize; the
# crash budget applies).
ENV_NO_ELASTIC = "TTD_NO_ELASTIC"


def classify_exit(returncode: int) -> str:
    """``clean`` | ``preemption`` | ``device_loss`` | ``crash``."""
    if returncode == 0:
        return "clean"
    if returncode == PREEMPTION_EXIT_CODE:
        return "preemption"
    if returncode == DEVICE_LOSS_EXIT_CODE:
        return "device_loss"
    return "crash"


@dataclasses.dataclass
class SupervisorResult:
    returncode: int
    attempts: int
    crashes: int
    preemptions: int
    gave_up: bool
    device_losses: int = 0


class TrainSupervisor:
    """Run ``argv`` as a child process until it exits clean, the crash
    budget is spent, or (optionally) preemptions stop being restartable.

    ``backoff_s`` doubles per budgeted crash (the exponent is the
    crash count inside ``restart_window_s`` when a window is set, the
    lifetime count otherwise — so with a window the delay decays back
    toward the base as old crashes age out), capped at
    ``backoff_max_s``, then jittered UP by up to ``backoff_jitter``
    of itself (decorrelating fleet-wide relaunch stampedes; 0
    disables).  Preemption and device-loss relaunches wait a flat
    ``backoff_s`` (no exponent — a maintenance event or dead chip is
    not a bug, but zero delay would let a child that exits at startup
    spin the loop unboundedly).  Device-loss relaunches are free of the
    CRASH budget but carry their own cap (``max_device_losses``): a
    mesh can only shrink so many times, so a child that keeps exiting
    113 — a flapping chip, or a misclassified persistent error — gives
    up instead of relaunching forever.

    The supervisor itself forwards SIGTERM/SIGINT to the live child and
    then stops relaunching (``handle_signals=True``, main thread only):
    a scheduler terminating the *supervisor* means the whole job should
    checkpoint and stop, not lose the relaunch loop out from under a
    training child mid-save.
    """

    def __init__(self, argv: Sequence[str], *,
                 max_restarts: int = 3,
                 backoff_s: float = 1.0,
                 backoff_max_s: float = 60.0,
                 backoff_jitter: float = 0.1,
                 restart_window_s: Optional[float] = None,
                 restart_on_preemption: bool = True,
                 elastic: bool = True,
                 max_device_losses: int = 16,
                 elastic_state_path: Optional[str] = None,
                 journal_path: Optional[str] = None,
                 env: Optional[dict] = None,
                 handle_signals: bool = True,
                 sleep=time.sleep,
                 rng: Optional[random.Random] = None):
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}")
        if backoff_s < 0:
            raise ValueError(f"backoff_s must be >= 0, got {backoff_s}")
        if not 0.0 <= backoff_jitter <= 1.0:
            raise ValueError(
                f"backoff_jitter must be in [0, 1], got {backoff_jitter}")
        if restart_window_s is not None and restart_window_s <= 0:
            raise ValueError(
                f"restart_window_s must be > 0 (None = lifetime), got "
                f"{restart_window_s}")
        if max_device_losses < 0:
            raise ValueError(
                f"max_device_losses must be >= 0, got {max_device_losses}")
        self.argv = list(argv)
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.restart_window_s = restart_window_s
        self.restart_on_preemption = restart_on_preemption
        self.max_device_losses = max_device_losses
        # TTD_NO_ELASTIC=1 wins over the constructor: the operator's
        # no-redeploy veto of mesh resizing (device loss then classifies
        # as a plain crash, budget and all).
        self.elastic = (elastic and os.environ.get(
            ENV_NO_ELASTIC, "0") in ("", "0"))
        self.journal_path = journal_path
        if elastic_state_path is None and self.elastic:
            # The child needs a path it can write WITHOUT a checkpoint
            # dir configured; a journal-DERIVED sidecar when there is a
            # journal (stem-scoped: supervisors journaling different
            # files into the same directory must not read each other's
            # survivor counts), a pid-scoped tmp path otherwise.
            if journal_path:
                stem = os.path.splitext(
                    os.path.basename(journal_path))[0]
                elastic_state_path = os.path.join(
                    os.path.dirname(os.path.abspath(journal_path)),
                    f"{stem}.elastic.json")
            else:
                elastic_state_path = os.path.join(
                    tempfile.gettempdir(),
                    f"ttd_elastic_{os.getpid()}.json")
        self.elastic_state_path = elastic_state_path
        self.env = env
        self.handle_signals = handle_signals
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._proc: Optional[subprocess.Popen] = None
        self._stop_signal: Optional[int] = None
        # Surviving device count adopted after a device-loss exit; every
        # subsequent launch exports it so the relaunched child builds
        # its mesh over the survivors.
        self._elastic_devices: Optional[int] = None

    def _journal(self, record: dict) -> None:
        # Journal lines double as flight-recorder instants, so attempt
        # boundaries/relaunches land on the same timeline as the
        # trainer's step spans (runtime.events; tools/trace_report.py
        # renders both).
        events.instant(
            "supervisor/" + str(record.get("event", "event")),
            **{k: v for k, v in record.items()
               if k != "event" and isinstance(v, (str, int, float, bool))})
        if not self.journal_path:
            return
        os.makedirs(os.path.dirname(os.path.abspath(self.journal_path)),
                    exist_ok=True)
        with open(self.journal_path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def _read_elastic_state(self) -> Optional[int]:
        """Surviving device count from the sidecar the dying child
        wrote (None when missing/unreadable/unknown — the relaunch
        then re-discovers its devices itself).  The sidecar is
        CONSUMED: a later device loss whose child failed to write one
        must read as unknown, not re-adopt this exit's stale count."""
        path = self.elastic_state_path
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                survivors = json.load(f).get("survivors")
            result = int(survivors) if survivors else None
        except (OSError, ValueError):
            logger.warning(
                "supervisor: unreadable elastic sidecar %s; relaunching "
                "with the device set unpinned", path, exc_info=True)
            result = None
        try:
            os.remove(path)
        except OSError:
            pass
        return result

    def _launch(self, attempt: int) -> int:
        env = dict(os.environ if self.env is None else self.env)
        env[ENV_ATTEMPT] = str(attempt)
        if self.elastic and self.elastic_state_path:
            env[ENV_ELASTIC_STATE] = self.elastic_state_path
        if self._elastic_devices is not None:
            env[ENV_ELASTIC_DEVICES] = str(self._elastic_devices)
        logger.info("supervisor attempt %d: %s", attempt,
                    " ".join(self.argv))
        # No stdout/stderr capture: the child IS the training job; its
        # logs stream to the operator exactly as an unsupervised run's
        # would.
        self._proc = subprocess.Popen(self.argv, env=env)
        try:
            # PEP 475: a forwarded signal interrupts this wait, runs the
            # handler, and the wait resumes until the child exits.
            return self._proc.wait()
        finally:
            self._proc = None

    def _forward_signal(self, signum, frame) -> None:
        self._stop_signal = signum
        logger.warning(
            "supervisor: got signal %d; forwarding to the child and "
            "stopping the relaunch loop after it exits", signum)
        proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            except OSError:      # child raced to exit
                pass

    @thread_role("supervisor")
    def run(self) -> SupervisorResult:
        # A sidecar left over from a PREVIOUS supervisor run is stale
        # state, not this run's survivor count: clear it so a device
        # loss whose child fails to write can never adopt it.
        if self.elastic and self.elastic_state_path:
            try:
                os.remove(self.elastic_state_path)
            except OSError:
                pass
        prev_handlers = {}
        if self.handle_signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev_handlers[sig] = signal.signal(
                        sig, self._forward_signal)
                except ValueError:      # not on the main thread
                    prev_handlers.clear()
                    break
        try:
            return self._run()
        finally:
            for sig, handler in prev_handlers.items():
                signal.signal(sig, handler)

    def _windowed_crashes(self, crash_times: list) -> int:
        """Crashes counted against the budget: all of them (lifetime),
        or only those inside the rolling ``restart_window_s``."""
        if self.restart_window_s is None:
            return len(crash_times)
        now = time.monotonic()
        return sum(1 for t in crash_times
                   if now - t <= self.restart_window_s)

    def _crash_backoff(self, consecutive: int) -> float:
        """Exponential in consecutive crashes, capped, jittered.

        The jitter multiplies UP (delay in [b, b·(1+jitter)]): shaving
        the delay below the base would defeat the backoff's purpose for
        a fraction of the fleet."""
        backoff = min(self.backoff_max_s,
                      self.backoff_s * 2 ** (consecutive - 1))
        if self.backoff_jitter and backoff:
            backoff *= 1.0 + self.backoff_jitter * self._rng.random()
        return backoff

    def _run(self) -> SupervisorResult:
        attempt = crashes = preemptions = device_losses = 0
        crash_times: list = []
        while True:
            if self._stop_signal is not None:
                # The stop signal landed while NO child was live (during
                # a backoff sleep, or between exit and relaunch): there
                # was nothing to forward it to, so stop here — launching
                # a fresh child against the scheduler's kill would run
                # the whole remaining job.
                logger.warning(
                    "supervisor: stop signal %d pending before relaunch; "
                    "not launching attempt %d", self._stop_signal, attempt)
                self._journal({"event": "stopped",
                               "signal": self._stop_signal,
                               "attempts": attempt, "crashes": crashes,
                               "preemptions": preemptions,
                               "rc": 128 + self._stop_signal})
                return SupervisorResult(128 + self._stop_signal, attempt,
                                        crashes, preemptions,
                                        gave_up=False)
            t0 = time.monotonic()
            rc = self._launch(attempt)
            duration = time.monotonic() - t0
            klass = classify_exit(rc)
            survivors = None
            if klass == "device_loss" and not self.elastic:
                logger.warning(
                    "supervisor: device-loss exit (rc=%d) with elastic "
                    "relaunch disabled (TTD_NO_ELASTIC/elastic=False); "
                    "classifying as a crash", rc)
                klass = "crash"
            backoff = 0.0
            if klass == "crash":
                crashes += 1
                crash_times.append(time.monotonic())
                backoff = self._crash_backoff(
                    self._windowed_crashes(crash_times))
            elif klass == "preemption":
                preemptions += 1
                # Flat base delay, no exponent: preemption relaunches
                # are free of the crash budget, so without it a child
                # exiting 143 right at startup would spin unboundedly.
                if self.restart_on_preemption:
                    backoff = self.backoff_s
            elif klass == "device_loss":
                device_losses += 1
                survivors = self._read_elastic_state()
                # Unknown survivors UNPIN the device set (the relaunch
                # re-discovers its devices) — keeping an older exit's
                # count would build a mesh over devices that may no
                # longer exist.
                self._elastic_devices = survivors
                # Same flat-delay rationale as preemption — hardware
                # loss is not a program bug and must not burn the crash
                # budget, but a zero delay would spin on a child that
                # loses its mesh at startup.
                backoff = self.backoff_s
            record = {"event": "exit", "attempt": attempt,
                      "rc": rc, "class": klass,
                      "duration_s": round(duration, 3),
                      "backoff_s": backoff, "time": time.time()}
            if klass == "device_loss":
                record["survivors"] = survivors
            self._journal(record)
            attempt += 1
            if klass != "clean" and self._stop_signal is not None:
                # The supervisor itself was told to stop: the child got
                # the forwarded signal (its 143 here means it saved and
                # stopped on purpose) — hand its code up, never relaunch
                # against the scheduler's will.
                self._journal({"event": "stopped",
                               "signal": self._stop_signal,
                               "attempts": attempt, "crashes": crashes,
                               "preemptions": preemptions, "rc": rc})
                return SupervisorResult(rc, attempt, crashes, preemptions,
                                        gave_up=False,
                                        device_losses=device_losses)
            if klass == "clean":
                logger.info("supervisor: clean exit after %d attempt(s)",
                            attempt)
                self._journal({"event": "done", "attempts": attempt,
                               "crashes": crashes,
                               "preemptions": preemptions})
                return SupervisorResult(0, attempt, crashes, preemptions,
                                        gave_up=False,
                                        device_losses=device_losses)
            if klass == "preemption":
                if not self.restart_on_preemption:
                    logger.warning(
                        "supervisor: preemption exit %d; restart "
                        "disabled — handing rc to the caller", rc)
                    self._journal({"event": "done", "attempts": attempt,
                                   "crashes": crashes,
                                   "preemptions": preemptions})
                    return SupervisorResult(rc, attempt, crashes,
                                            preemptions, gave_up=False,
                                            device_losses=device_losses)
                logger.warning(
                    "supervisor: preemption exit (rc=%d); relaunching "
                    "in %.2fs (crash budget untouched: %d/%d)", rc,
                    backoff, crashes, self.max_restarts)
                if backoff:
                    self._sleep(backoff)
                continue
            if klass == "device_loss":
                if device_losses > self.max_device_losses:
                    # A mesh can only shrink so many times: a child
                    # that KEEPS exiting 113 (flapping chip, unscoped
                    # fault plan, misclassified persistent error) must
                    # not relaunch forever just because the exits are
                    # crash-budget-free.
                    logger.error(
                        "supervisor: %d device-loss exits exceeded "
                        "max_device_losses=%d; giving up",
                        device_losses, self.max_device_losses)
                    self._journal({"event": "giveup", "attempts": attempt,
                                   "crashes": crashes,
                                   "preemptions": preemptions,
                                   "device_losses": device_losses,
                                   "rc": rc})
                    return SupervisorResult(
                        rc, attempt, crashes, preemptions, gave_up=True,
                        device_losses=device_losses)
                # Free of the crash budget (hardware died, not the
                # program); the relaunch builds its mesh over the
                # survivors (ENV_ELASTIC_DEVICES) and restores the
                # latest checkpoint resharded onto it.
                logger.warning(
                    "supervisor: device-loss exit (rc=%d, survivors=%s); "
                    "relaunching on the surviving devices in %.2fs "
                    "(crash budget untouched: %d/%d)", rc, survivors,
                    backoff, self._windowed_crashes(crash_times),
                    self.max_restarts)
                self._journal({"event": "resize",
                               "survivors": survivors,
                               "attempt": attempt})
                if backoff:
                    self._sleep(backoff)
                continue
            # crash — budget accounting over the rolling window when one
            # is configured: a burst of correlated crashes ages out of
            # the window instead of permanently exhausting a long run's
            # protection.
            budget_crashes = self._windowed_crashes(crash_times)
            if budget_crashes > self.max_restarts:
                logger.error(
                    "supervisor: crash rc=%d exhausted the restart "
                    "budget (%d crashes%s > %d restarts); giving up",
                    rc, budget_crashes,
                    ("" if self.restart_window_s is None else
                     f" in the last {self.restart_window_s:g}s"),
                    self.max_restarts)
                self._journal({"event": "giveup", "attempts": attempt,
                               "crashes": crashes,
                               "preemptions": preemptions, "rc": rc})
                return SupervisorResult(rc, attempt, crashes, preemptions,
                                        gave_up=True,
                                        device_losses=device_losses)
            logger.warning(
                "supervisor: crash rc=%d (%s); relaunching in %.2fs "
                "(crash %d/%d)", rc,
                f"signal {-rc}" if rc < 0 else "exit",
                backoff, budget_crashes, self.max_restarts)
            if backoff:
                self._sleep(backoff)


SUPERVISOR_FLAGS = {
    # flag -> takes a value?  (the strip list for child argv rebuild)
    "--supervise": False,
    "--max-restarts": True,
    "--restart-backoff": True,
    "--restart-backoff-max": True,
    "--restart-window": True,
    "--restart-jitter": True,
    "--no-restart-on-preemption": False,
    "--no-elastic": False,
    "--max-device-losses": True,
    "--supervisor-journal": True,
}


def strip_supervisor_flags(argv: Sequence[str]) -> list:
    """Remove supervisor-only flags from a CLI argv, producing the
    child's argv tail (the supervisor must not recurse)."""
    out = []
    i = 0
    args = list(argv)
    while i < len(args):
        a = args[i]
        flag = a.split("=", 1)[0]
        if flag in SUPERVISOR_FLAGS:
            if SUPERVISOR_FLAGS[flag] and "=" not in a:
                i += 1              # consume the separate value
            i += 1
            continue
        out.append(a)
        i += 1
    return out


def supervise_cli(argv: Sequence[str], args) -> int:
    """``launch.py --supervise`` entry: re-run this CLI (minus the
    supervisor flags) under a ``TrainSupervisor`` built from ``args``."""
    child = [sys.executable, "-m", "tensorflow_train_distributed_tpu",
             *strip_supervisor_flags(argv)]
    journal = args.supervisor_journal
    if journal is None and args.checkpoint_dir:
        journal = os.path.join(args.checkpoint_dir, "supervisor.jsonl")
    sup = TrainSupervisor(
        child,
        max_restarts=args.max_restarts,
        backoff_s=args.restart_backoff,
        backoff_max_s=args.restart_backoff_max,
        backoff_jitter=args.restart_jitter,
        restart_window_s=args.restart_window or None,
        restart_on_preemption=not args.no_restart_on_preemption,
        elastic=not args.no_elastic,
        max_device_losses=args.max_device_losses,
        journal_path=journal,
    )
    return sup.run().returncode
