"""Profiling and observability: XPlane traces, annotations, memory stats.

The reference profiles through the same underlying stack this module wraps:
TraceMe annotations recorded into XPlane protos viewed in TensorBoard
(SURVEY.md §5.1 — ``python/profiler/profiler_v2.py:81/130``, C++
``tsl/profiler/lib/traceme.h``; Keras hook ``TensorBoard(profile_batch=...)``
``tf_keras/src/callbacks.py:2371``).  JAX ships the identical XPlane
machinery as ``jax.profiler``, so traces land in the same TensorBoard
profile plugin — including TPU-side HLO op breakdowns this framework gets
for free.

Three surfaces:

- ``trace(logdir)`` / ``start_trace`` / ``stop_trace`` — whole-window
  capture (reference ``tf.profiler.experimental.start/stop``).
- ``annotate(name)`` / ``annotate_function`` — host-side named spans that
  nest inside the trace (reference ``tf.profiler.experimental.Trace``).
- ``ProfileCallback`` — step-window capture inside ``Trainer.fit``
  (reference ``TensorBoard(profile_batch=(a, b))``).

Plus ``device_memory_stats`` for HBM occupancy (per-device bytes in use),
the observability hook the reference exposes via
``tf.config.experimental.get_memory_info``.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Iterator, Optional

import jax

from tensorflow_train_distributed_tpu.training.callbacks import Callback

logger = logging.getLogger(__name__)


def start_trace(logdir: str) -> None:
    """Begin an XPlane trace capture into ``logdir`` (chief process only)."""
    if jax.process_index() == 0:
        jax.profiler.start_trace(logdir)
        logger.info("profiler trace started → %s", logdir)


def stop_trace() -> None:
    if jax.process_index() == 0:
        jax.profiler.stop_trace()
        logger.info("profiler trace stopped")


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a trace for the duration of the block."""
    start_trace(logdir)
    try:
        yield
    finally:
        stop_trace()


def start_profiler_server(port: int):
    """On-demand remote capture: the analog of the reference's
    ``tf.profiler.experimental.server.start`` (``profiler_v2.py:169``) —
    TensorBoard's "Capture profile" dialog (or
    ``jax.profiler.trace_remote``) can then pull a trace from a live
    training job without any pre-planned --profile-dir window.

    jax keeps the running server in a module-level global until
    ``jax.profiler.stop_server()``; the returned handle is informational.
    """
    try:
        server = jax.profiler.start_server(port)
    except ValueError as e:
        # jax allows one server per process; a second launch.run in the
        # same process keeps the existing one rather than crashing.
        logger.warning("profiler server not started (%s); keeping the "
                       "existing one", e)
        return None
    logger.info("profiler server listening on port %d", port)
    return server


def annotate(name: str, **kwargs):
    """Named host-side span (TraceMe); nests under an active trace."""
    return jax.profiler.TraceAnnotation(name, **kwargs)


def annotate_function(fn, name: Optional[str] = None):
    """Decorator form of ``annotate``."""
    return jax.profiler.annotate_function(fn, name=name)


def device_memory_stats() -> list[dict]:
    """Per-device memory stats (bytes_in_use / peak / limit where known).

    CPU/test backends report no stats; entries then carry only the device
    id so callers can still enumerate the fleet.
    """
    stats = []
    for d in jax.local_devices():
        s = d.memory_stats() or {}
        stats.append({
            "device": str(d),
            "bytes_in_use": s.get("bytes_in_use"),
            "peak_bytes_in_use": s.get("peak_bytes_in_use"),
            "bytes_limit": s.get("bytes_limit"),
        })
    return stats


class ProfileCallback(Callback):
    """Capture a trace over a step window during ``fit``.

    ``start_step``/``stop_step`` follow the reference's
    ``profile_batch=(start, stop)`` contract: capture begins after the step
    *before* ``start_step`` completes and ends after ``stop_step``.  Steps
    are observed at the trainer's ``log_every`` granularity, so the
    realized window snaps to log boundaries — always spanning at least the
    requested steps.
    """

    def __init__(self, logdir: str, *, start_step: int = 10,
                 stop_step: int = 20):
        if stop_step < start_step:
            raise ValueError(
                f"stop_step={stop_step} < start_step={start_step}")
        self.logdir = logdir
        self.start_step = start_step
        self.stop_step = stop_step
        self._active = False
        self._done = False

    def on_step_end(self, step, metrics):
        if self._done:
            return
        if not self._active and step >= self.start_step - 1:
            start_trace(self.logdir)
            self._active = True
            return
        if self._active and step >= self.stop_step:
            stop_trace()
            self._active = False
            self._done = True

    def on_train_end(self, state):
        if self._active:  # window extended past the end of training
            stop_trace()
            self._active = False
            self._done = True


class SpeedMonitor(Callback):
    """Rolling step-time / throughput stats, queryable and JSONL-loggable.

    The quantitative face of observability (§5.5): wall-time per optimizer
    step and examples/sec, aggregated between log events.  ``summary()``
    returns the final numbers — what ``bench.py`` and regression tests
    read.
    """

    def __init__(self, examples_per_step: Optional[int] = None):
        from tensorflow_train_distributed_tpu.training.callbacks import (
            StepRateTracker,
        )

        self.examples_per_step = examples_per_step
        self._tracker = StepRateTracker()
        self.step_times_ms: list[float] = []

    def on_step_end(self, step, metrics):
        # Burst-aware: one sample per drain window, not per callback call
        # (see StepRateTracker — naive per-call deltas are meaningless
        # under fit's log_every batching).
        ms = self._tracker.update(step)
        if ms is not None:
            self.step_times_ms.append(ms)

    def summary(self) -> dict:
        if not self.step_times_ms:
            return {}
        import numpy as np

        arr = np.asarray(self.step_times_ms)
        out = {
            "mean_step_ms": float(arr.mean()),
            "median_step_ms": float(np.median(arr)),
            "p90_step_ms": float(np.percentile(arr, 90)),
        }
        if self.examples_per_step:
            out["examples_per_sec"] = (
                self.examples_per_step / (out["median_step_ms"] / 1e3))
        return out
