"""Device mesh construction and strategy presets.

The reference exposes a zoo of strategy classes — ``MirroredStrategy``
(``mirrored_strategy.py:200``), ``MultiWorkerMirroredStrategy``
(``collective_all_reduce_strategy.py:57``), ``ParameterServerStrategyV2``
(``parameter_server_strategy_v2.py:77``), a Horovod hook, and DTensor meshes
(``dtensor/python/layout.py:54``).  On TPU all of those are one thing: an SPMD
program over a named ``jax.sharding.Mesh``.  What survives of the "strategy"
concept is a *mesh preset*: a named assignment of the device grid to logical
parallelism axes.

Axes (any may be size 1):

- ``data``     — pure data parallelism (replicated params, sharded batch).
- ``fsdp``     — data parallelism with parameters/opt-state sharded over it
                 (ZeRO-3 style; batch is sharded over data×fsdp jointly).
- ``tensor``   — tensor/model parallelism (Megatron-style within-layer).
- ``seq``      — sequence/context parallelism (ring attention / Ulysses).
- ``expert``   — expert parallelism for MoE layers.
- ``pipeline`` — pipeline stages.

Presets keep the reference's ``--strategy`` CLI contract meaningful
(``mirrored`` / ``multi_worker_mirrored`` / ``tpu`` → ``dp``; ``ps`` →
rejected, see ``distributed._from_tf_config``; ``dtensor`` → ``dp_tp``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: outermost (slowest-varying, DCN-adjacent) first.
# Data-parallel axes ride DCN across slices; tensor/seq want the fastest ICI
# links, so they sit innermost — mesh_utils assigns the last mesh dims to the
# most tightly coupled device dims.
AXES = ("pipeline", "data", "fsdp", "expert", "seq", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes per logical axis; ``-1`` on at most one axis means "infer".

    ``strategy`` may name a preset (see ``STRATEGY_PRESETS``) in which case
    unspecified axes come from the preset.
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipeline: int = 1
    strategy: Optional[str] = None

    def axis_sizes(self) -> dict[str, int]:
        return {
            "pipeline": self.pipeline,
            "data": self.data,
            "fsdp": self.fsdp,
            "expert": self.expert,
            "seq": self.seq,
            "tensor": self.tensor,
        }

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Concrete per-axis sizes for an ``n_devices`` mesh."""
        sizes = self.axis_sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"At most one axis may be -1, got {unknown}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[unknown[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"Mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}"
            )
        return sizes


# --strategy name → MeshConfig. Reference-strategy names map onto their SPMD
# equivalents so existing launch scripts keep working.
STRATEGY_PRESETS: dict[str, MeshConfig] = {
    "dp": MeshConfig(data=-1),
    "mirrored": MeshConfig(data=-1),                  # reference configs[0]
    "multi_worker_mirrored": MeshConfig(data=-1),     # reference configs[1]
    "horovod": MeshConfig(data=-1),                   # reference configs[3]
    "tpu": MeshConfig(data=-1),                       # reference north-star flag
    "fsdp": MeshConfig(data=1, fsdp=-1),
    "dp_fsdp": MeshConfig(data=-1, fsdp=8),
    "dp_tp": MeshConfig(data=-1, tensor=4),           # DTensor 2-D (data×model)
    "dtensor": MeshConfig(data=-1, tensor=4),         # reference configs[4]
    "dp_sp": MeshConfig(data=-1, seq=4),
    "dp_tp_sp": MeshConfig(data=-1, seq=2, tensor=4),
    "fsdp_tp": MeshConfig(data=1, fsdp=-1, tensor=4),
    "dp_ep": MeshConfig(data=-1, expert=4),
    # Pipeline axis: scanned-block models with ``pipeline_microbatches``
    # set (e.g. the llama family) run the GPipe schedule
    # (``parallel.pipeline.gpipe_layers``) over it — layer groups per
    # stage, microbatched ticks, ppermute hops.
    "dp_pp": MeshConfig(data=-1, pipeline=2),
}


def force_platform(platform: Optional[str] = None,
                   num_cpu_devices: Optional[int] = None) -> None:
    """Re-target the JAX backend, even if one is already initialized.

    Plain ``jax.config.update`` is silently ignored (``jax_platforms``) or
    rejected (``jax_num_cpu_devices``) once a backend exists — which it
    always does under launchers whose sitecustomize imports jax at
    interpreter startup.  Resetting via ``clear_backends`` first makes the
    override effective regardless of initialization order (the late-bound
    analog of the reference's logical-device split in
    ``tensorflow/python/distribute/test_util.py:131``).
    """
    from jax.extend import backend as jax_backend

    if num_cpu_devices and not platform:
        # A device-count override only means anything on the CPU backend;
        # without this the flag would silently no-op under a pinned
        # non-CPU platform.
        platform = "cpu"
    jax_backend.clear_backends()
    if platform:
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices:
        set_cpu_device_count(num_cpu_devices)


def set_cpu_device_count(n: int) -> None:
    """Set the CPU backend's device count, portably across jax versions.

    jax >= 0.5 has the ``jax_num_cpu_devices`` config; on jax < 0.5 the
    count is an XLA flag, read when the CPU backend (re-)initializes —
    so this must run before the backend is (re)built (``force_platform``
    clears backends first; fresh child processes call it before any
    device API).  Replaces any pre-existing count flag rather than
    appending a duplicate.
    """
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        import os

        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)


def strategy_preset(name: str, n_devices: Optional[int] = None) -> MeshConfig:
    """Look up a preset, shrinking fixed axes to fit small device counts.

    A preset like ``dp_tp`` (tensor=4) on a 2-device test mesh degrades to
    tensor=2 rather than failing — mirrors the reference's behavior of running
    any strategy on whatever devices exist.
    """
    if name == "ps" or name == "parameter_server":
        raise ValueError(
            "ParameterServerStrategy is not supported: this framework is "
            "SPMD-only. Use --strategy=dp_tp (the DTensor-style mesh the "
            "reference's north star prescribes for the BERT config)."
        )
    if name not in STRATEGY_PRESETS:
        raise ValueError(
            f"Unknown strategy {name!r}; available: {sorted(STRATEGY_PRESETS)}"
        )
    cfg = STRATEGY_PRESETS[name]
    if n_devices is None:
        return cfg
    return MeshConfig(strategy=name,
                      **_shrink_sizes(cfg.axis_sizes(), n_devices))


def _shrink_sizes(sizes: dict, n_devices: int) -> dict:
    """Shrink fixed (>1, non-inferred) axes until the mesh fits
    ``n_devices`` — halving non-dividers first, then the largest fixed
    axis until the fixed product divides the device count."""
    sizes = dict(sizes)
    fixed_axes = [a for a, s in sizes.items() if s not in (1, -1)]
    for axis in fixed_axes:
        while sizes[axis] > 1 and n_devices % sizes[axis]:
            sizes[axis] //= 2
        sizes[axis] = max(1, min(sizes[axis], n_devices))
    fixed = math.prod(s for s in sizes.values() if s != -1)
    while fixed > n_devices or n_devices % fixed:
        # Shrink the largest fixed axis until the mesh fits.
        big = max(fixed_axes, key=lambda a: sizes[a], default=None)
        if big is None or sizes[big] == 1:
            break
        sizes[big] //= 2
        fixed = math.prod(s for s in sizes.values() if s != -1)
    return sizes


def degrade_to_fit(config: MeshConfig, n_devices: int) -> MeshConfig:
    """Nearest valid layout for ``config`` on ``n_devices`` devices.

    The elastic-relaunch divisibility degrade: a run configured with
    explicit ``--mesh`` axis sizes that no longer fit the surviving
    device set comes back with its fixed axes shrunk (same rules as
    ``strategy_preset``'s shrink-to-fit) and any explicitly-pinned
    product mismatch absorbed by the data axis — training continues on
    the smaller mesh instead of crash-looping the relaunch.  Returns
    ``config`` unchanged when it already resolves.
    """
    try:
        config.resolve(n_devices)
        return config
    except ValueError:
        pass
    sizes = _shrink_sizes(config.axis_sizes(), n_devices)
    probe = MeshConfig(strategy=config.strategy, **sizes)
    try:
        probe.resolve(n_devices)
    except ValueError:
        # Fixed axes fit but the explicit product mismatches (e.g.
        # data pinned to the old device count): let data absorb the
        # remainder.
        sizes["data"] = -1
        probe = MeshConfig(strategy=config.strategy, **sizes)
        probe.resolve(n_devices)  # raises only if truly unsatisfiable
    return probe


def hybrid_shapes(sizes: dict[str, int],
                  dcn_axes: Optional[dict[str, int]],
                  num_slices: int) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Split per-axis totals into (ici_shape, dcn_shape) for a multi-slice
    mesh (``mesh_utils.create_hybrid_device_mesh`` contract: per-dim totals
    = ici × dcn, product of dcn dims = number of slices).

    ``dcn_axes`` names how slices divide each logical axis (e.g.
    ``{"data": 4}`` = 4 slices data-parallel over DCN).  ``None`` infers the
    default placement: all slices on the outermost axis whose size they
    divide — DCN traffic belongs on gradient allreduce (data/fsdp), never on
    tensor/seq collectives (AXES order encodes that preference).
    """
    if dcn_axes is None:
        # Only data-like axes may be inferred: tensor/seq collectives on
        # DCN would silently destroy step time, so a mesh whose data-like
        # axes can't absorb the slices must be configured explicitly.
        for a in ("pipeline", "data", "fsdp", "expert"):
            if sizes[a] >= num_slices and sizes[a] % num_slices == 0:
                dcn_axes = {a: num_slices}
                break
        else:
            raise ValueError(
                f"cannot place {num_slices} slices on any data-like axis "
                f"of {sizes} (tensor/seq are never inferred — their "
                "collectives belong on ICI); pass dcn_axes explicitly")
    if math.prod(dcn_axes.values()) != num_slices:
        raise ValueError(
            f"dcn_axes {dcn_axes} product must equal the slice count "
            f"{num_slices}")
    for a, d in dcn_axes.items():
        if a not in sizes:
            raise ValueError(f"unknown dcn axis {a!r}")
        if d < 1:
            raise ValueError(f"dcn factor for {a!r} must be >= 1, got {d}")
        if sizes[a] % d:
            raise ValueError(
                f"axis {a!r} of size {sizes[a]} not divisible by its DCN "
                f"factor {d}")
    ici = tuple(sizes[a] // dcn_axes.get(a, 1) for a in AXES)
    dcn = tuple(dcn_axes.get(a, 1) for a in AXES)
    return ici, dcn


def build_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_split_physical_axes: bool = False,
    dcn_axes: Optional[dict[str, int]] = None,
) -> Mesh:
    """Build a named ``Mesh`` over the device grid.

    On TPU, ``mesh_utils.create_device_mesh`` lays logical axes onto the
    physical torus so the innermost axes (tensor/seq) get contiguous ICI
    neighbours — the TPU-native analog of the reference's
    ``DeviceAssignment.build`` (``tpu/device_assignment.py:343``) computing
    replica→core mappings.  On CPU/test backends it falls back to a plain
    reshape.

    Multi-slice (several ICI islands joined by DCN — the topology the
    reference reaches with MultiWorkerMirroredStrategy over NCCL+gRPC):
    detected via device ``slice_index``; the hybrid mesh keeps each slice's
    devices ICI-contiguous and places the ``dcn_axes`` factors (default:
    outermost data-like axis) across slices, so XLA routes exactly those
    collectives over DCN.
    """
    if config is None:
        config = MeshConfig(data=-1)
    devices = list(devices if devices is not None else jax.devices())
    if config.strategy is not None and all(
        s == 1 for a, s in config.axis_sizes().items() if a != "data"
    ) and config.data == -1:
        # Bare MeshConfig(strategy=...) — resolve the preset against the real
        # device count so shrink-to-fit applies (e.g. dp_tp on 1 chip).
        config = strategy_preset(config.strategy, len(devices))
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        if len(slice_ids) > 1 or dcn_axes:
            ici_shape, dcn_shape = hybrid_shapes(
                sizes, dcn_axes, max(len(slice_ids), 1))
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
        else:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
    else:
        if dcn_axes:
            # No slice structure on CPU/test backends — placement is moot,
            # but the factorization is still validated so multi-slice CLI
            # invocations (--dcn) dry-run correctly on the test mesh.
            hybrid_shapes(sizes, dcn_axes,
                          math.prod(dcn_axes.values()))
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes the global batch is sharded over (data-parallel-like axes)."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape[a] > 1) or ("data",)


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape["data"] * mesh.shape["fsdp"]
