"""Memory-budget discipline: HBM allocator registry + live-byte sanitizer.

Every capacity lever in this stack — ``--kv-pool-blocks`` oversizing,
int8 pools, elastic worker packing — bets on HBM headroom that nothing
used to see or enforce: an OOM was an opaque XLA error after the fact,
and admission reasoned about free blocks, not bytes.  This module is
the ttd-lint framework's THIRD vertical (locks → ``lockcheck``,
compiles → ``compilecheck``, memory → here), same two-half shape:

- **static checker** (``memcheck``, registered in ``core``): a module
  that declares any ``@memory_budget`` pool is a HOT ALLOCATOR MODULE
  (``serving.py`` and ``training/trainer.py`` are REQUIRED to be), and
  inside one every host-side device allocation (``jnp.zeros`` /
  ``jnp.ones`` / ``jnp.full`` / ``jnp.empty`` / ``jax.device_put``)
  must be reachable from a sanctioned owner: an ``@memory_budget``
  allocator, a jit program (its allocations are the program's working
  set, accounted at ITS caller's pool), or an ``jax.eval_shape`` thunk
  (trace-only, never allocates).  A device allocation outside those is
  an unbudgeted pool in the making.  The checker also audits
  DONATION-DEFEATING ALIASING at call sites of ``@compile_site``
  programs: passing ``self._cache`` in a donated position without
  rebinding it from the result keeps the old buffer live behind the
  donation — XLA cannot actually reuse it, and peak HBM silently
  doubles (the exact failure mode the ``donates=`` cross-check guards
  at the declaration; this guards the call).  And every
  ``@memory_budget`` must declare a budget (``budget_bytes`` or
  ``budget_fn``) — a pool without a budget is a gauge, not a
  discipline.

- **runtime sanitizer** (``TTD_MEMCHECK=1``; ``TTD_NO_MEMCHECK=1`` is
  the live escape hatch, re-read per allocation through the
  ``os.environ._data`` fast path): annotated allocators charge a
  per-``(owner, pool)`` ledger with the byte size of the tree they
  mint (host metadata only — shapes and dtypes, never a device sync).
  BEFORE the allocation runs, the projected bytes (from the spec's
  ``project_fn`` — the engine's memoized cache ``eval_shape`` — or the
  memo of a previous identical-signature allocation) are checked
  against the owner's declared budget, and the first allocation that
  would exceed it raises ``MemoryBudgetError`` with the offending
  allocation DIFFED against the owner's live set — pool by pool,
  allocation by allocation — instead of letting XLA OOM later with no
  attribution.  Charges are released when the owner dies, when the
  minted leaves die (transient allocations), or when a same-site
  same-signature allocation replaces them (rebuilt pools); every
  charge records a ``memory/<pool>`` flight-recorder span and feeds
  the ``ttd_engine_hbm_bytes{pool=...}`` gauge family, with a
  ``memory/near_miss`` instant once a pool crosses 90% of its budget.

Accounting honesty: the ledger tracks ALLOCATOR-MINTED buffers.  A
donating jit program (``_prefill_piece`` threading a batch-1 cache)
returns same-shaped SUCCESSOR buffers the wrapper never sees, so a
``lifetime="leaf"`` charge ends at the first donation — transient
prefill charges are therefore an admission-time budget gate, not a
steady-state gauge, while ``lifetime="owner"`` pools (the KV block
pool grid caches, the trainer state) are exact for the owner's whole
life.  That split matches what HBM budgeting needs: the constant pools
dominate, and the transient gate still catches the burst that would
have OOMed.
"""

from __future__ import annotations

import ast
import itertools
import os
import threading
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.lint.core import (
    Finding,
    register_checker,
)
from tensorflow_train_distributed_tpu.runtime.lint.dispatch import (
    _decorator_name,
    _dotted,
    _is_jit_decorated,
)

CHECKER = "memcheck"

_ARM_ENV = "TTD_MEMCHECK"
_KILL_ENV = "TTD_NO_MEMCHECK"


class MemoryBudgetError(RuntimeError):
    """An allocation would exceed its owner's declared HBM budget."""


# -- arming ----------------------------------------------------------------


def _truthy(v: Optional[str]) -> bool:
    return v is not None and v not in ("", "0")


def armed() -> bool:
    """``TTD_MEMCHECK`` truthy and not vetoed by ``TTD_NO_MEMCHECK`` —
    checked at decoration time (allocators wrap at import, the
    lockcheck/compilecheck contract: arm BEFORE importing the
    package)."""
    if _truthy(os.environ.get(_KILL_ENV)):
        return False
    return _truthy(os.environ.get(_ARM_ENV))


# Re-read per allocation (an operator shell can disarm a misbehaving
# sanitizer live, no redeploy) — the shared fast-path reader.
_vetoed = events.make_env_flag_reader(_KILL_ENV)


# -- pool registry ---------------------------------------------------------


@dataclass(frozen=True)
class PoolSpec:
    """One allocator's declared memory discipline."""

    site: str
    pool: object                   # str, or callable(*args, **kw) -> str
    budget_bytes: Optional[int] = None
    budget_fn: Optional[Callable] = None
    project_fn: Optional[Callable] = None
    lifetime: object = "owner"     # "owner" | "leaf", or callable
    method: bool = False           # args[0] is the owning instance


@dataclass
class _Alloc:
    label: str
    nbytes: int
    leaves_left: int = 0           # leaf-lifetime bookkeeping


@dataclass
class _OwnerLedger:
    """Live allocations of one owner (engine/trainer/None=module),
    split by pool."""

    pools: Dict[str, Dict[int, _Alloc]] = field(default_factory=dict)
    peak: Dict[str, int] = field(default_factory=dict)


_STATE_LOCK = threading.Lock()
_SITES: Dict[str, PoolSpec] = {}
_LEDGERS: Dict[object, _OwnerLedger] = {}
# (site, owner token, signature) -> bytes: the projection memo — a
# repeat allocation of a known signature is budget-checked BEFORE it
# runs even without a project_fn.  The OWNER is part of the key: two
# engines can share a signature (same slots/draft/grid args) while
# their configs mint very different trees — one engine's bytes must
# never project another's.
_PROJ: Dict[tuple, int] = {}
_AIDS = itertools.count(1)
_TOKENS = itertools.count(1)
_IN_ALLOC = threading.local()      # re-entrancy guard: outermost wins


def register_site(spec: PoolSpec) -> PoolSpec:
    with _STATE_LOCK:
        _SITES[spec.site] = spec
    return spec


def sites() -> Tuple[str, ...]:
    """Registered allocator sites (populated at import of annotated
    modules)."""
    with _STATE_LOCK:
        return tuple(sorted(_SITES))


def reset() -> None:
    """Forget every ledger and projection (test isolation)."""
    with _STATE_LOCK:
        _RELEASES.clear()
        _LEDGERS.clear()
        _PROJ.clear()


def tree_bytes(tree) -> int:
    """Total device bytes of a pytree's array leaves — pure host
    metadata (shape × itemsize), no sync.  ShapeDtypeStructs count like
    arrays, so eval_shape output projects for free."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape)) * np.dtype(dtype).itemsize
    return total


def _purge_owner(tok) -> None:
    with _STATE_LOCK:
        _LEDGERS.pop(tok, None)
        # The projection memo is owner-keyed too: a long-lived armed
        # process churning engines must not accumulate dead owners'
        # entries (the leak-catcher must not itself leak).
        for k in [k for k in _PROJ if k[1] == tok]:
            del _PROJ[k]


def _owner_token(x) -> object:
    """Stable ledger key for an allocation's owning instance, with a
    finalizer purging the ledger at owner gc (the compilecheck
    instance-token idiom: ``id()`` alone would merge a dead engine's
    ledger into whatever reuses its address).  Attachment is locked:
    a gateway handler thread's validate_request and the driver's
    first allocation may both mint the first token, and a lost race
    would split one engine's ledger over two keys."""
    if x is None:
        return None
    tok = getattr(x, "__ttd_mc_token__", None)
    if tok is not None:
        return ("tok", tok)
    with _STATE_LOCK:
        tok = getattr(x, "__ttd_mc_token__", None)
        if tok is not None:
            return ("tok", tok)
        try:
            tok = next(_TOKENS)
            object.__setattr__(x, "__ttd_mc_token__", tok)
        except (AttributeError, TypeError):
            return ("id", type(x).__name__, id(x))
    entry = ("tok", tok)
    try:
        weakref.finalize(x, _purge_owner, entry)
    except TypeError:              # pragma: no cover - not weakref-able
        pass
    return entry


# -- ledger ----------------------------------------------------------------


def live_bytes(owner=None, pool: Optional[str] = None) -> int:
    """Live charged bytes — for one owner instance (pass the object),
    one pool name, both, or everything (``owner=None`` sums every
    owner, module-level allocations included)."""
    tok = _owner_token(owner) if owner is not None else None
    total = 0
    with _STATE_LOCK:
        _drain_releases_locked()
        for otok, ledger in _LEDGERS.items():
            if owner is not None and otok != tok:
                continue
            for pname, allocs in ledger.pools.items():
                if pool is not None and pname != pool:
                    continue
                total += sum(a.nbytes for a in allocs.values())
    return total


def _live_tok(tok) -> int:
    """Live bytes of ONE ledger key (the wrapper's budget-check read:
    ``tok`` may be None for module-level allocators, which
    ``live_bytes(owner=None)`` cannot express)."""
    with _STATE_LOCK:
        _drain_releases_locked()
        ledger = _LEDGERS.get(tok)
        if ledger is None:
            return 0
        return sum(a.nbytes for allocs in ledger.pools.values()
                   for a in allocs.values())


def _replaceable_bytes(tok, pool: str, site: str) -> int:
    """Bytes of the owner-lifetime charge a same-site allocation is
    about to REPLACE (``_charge`` deletes it) — the pre-allocation
    budget check must not count both the old pool and its rebuild, or
    any rebuild with budget < 2x the pool spuriously raises."""
    with _STATE_LOCK:
        _drain_releases_locked()
        ledger = _LEDGERS.get(tok)
        if ledger is None:
            return 0
        allocs = ledger.pools.get(pool) or {}
        return sum(a.nbytes for a in allocs.values()
                   if a.label == site)


def live_by_pool() -> Dict[str, float]:
    """``{pool: live_bytes}`` across every owner — the
    ``ttd_engine_hbm_bytes{pool=...}`` gauge family's source (and the
    per-worker stats-frame payload)."""
    out: Dict[str, float] = {}
    with _STATE_LOCK:
        _drain_releases_locked()
        for ledger in _LEDGERS.values():
            for pname, allocs in ledger.pools.items():
                if allocs:
                    out[pname] = out.get(pname, 0.0) + float(
                        sum(a.nbytes for a in allocs.values()))
    return out


def peak_by_pool() -> Dict[str, float]:
    """``{pool: peak_live_bytes}`` across owners (forensics)."""
    out: Dict[str, float] = {}
    with _STATE_LOCK:
        _drain_releases_locked()
        for ledger in _LEDGERS.values():
            for pname, peak in ledger.peak.items():
                out[pname] = max(out.get(pname, 0.0), float(peak))
    return out


def _live_set_locked(tok) -> List[tuple]:
    ledger = _LEDGERS.get(tok)
    if ledger is None:
        return []
    out = []
    for pname, allocs in ledger.pools.items():
        for a in allocs.values():
            out.append((pname, a.label, a.nbytes))
    return out


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.1f} {unit}" if unit != "B"
                    else f"{int(n)} {unit}")
        n /= 1024
    return f"{n:.1f} GiB"          # pragma: no cover - loop returns


def _budget_error(site: str, pool: str, projected: int, budget: int,
                  tok) -> MemoryBudgetError:
    with _STATE_LOCK:
        _drain_releases_locked()
        live = _live_set_locked(tok)
    total = sum(b for _, _, b in live)
    lines = [f"  live {p}/{label}: {_fmt_bytes(b)}"
             for p, label, b in sorted(live, key=lambda t: -t[2])]
    listing = "\n".join(lines) or "  (no live allocations)"
    return MemoryBudgetError(
        f"memory budget exceeded at allocator '{site}': allocating "
        f"{_fmt_bytes(projected)} into pool '{pool}' would put the "
        f"owner at {_fmt_bytes(total + projected)} live, over its "
        f"declared budget of {_fmt_bytes(budget)}.  The offending "
        f"allocation against the live set:\n{listing}\n"
        f"Shrink the pool (e.g. --kv-pool-blocks), raise the declared "
        f"budget, or find the leak in the listing above")


# Leaf finalizers run at gc time, which any allocation can trigger —
# including allocations INSIDE a _STATE_LOCK section (a dict insert in
# _charge).  A finalizer taking _STATE_LOCK there would self-deadlock,
# so finalizers only APPEND to this lock-free deque; every ledger
# reader/writer drains it under the lock first.
from collections import deque as _deque

_RELEASES: "_deque" = _deque()


def _release(tok, pool: str, aid: int, nbytes: int) -> None:
    _RELEASES.append((tok, pool, aid, nbytes))


def _drain_releases_locked() -> None:
    """Apply queued leaf releases (caller holds ``_STATE_LOCK``)."""
    while True:
        try:
            tok, pool, aid, nbytes = _RELEASES.popleft()
        except IndexError:
            return
        ledger = _LEDGERS.get(tok)
        if ledger is None:
            continue
        allocs = ledger.pools.get(pool)
        if allocs is None:
            continue
        a = allocs.get(aid)
        if a is None:
            continue
        a.nbytes = max(0, a.nbytes - nbytes)
        a.leaves_left -= 1
        if a.leaves_left <= 0 or a.nbytes == 0:
            del allocs[aid]


def _charge(tok, pool: str, site: str, nbytes: int, result,
            lifetime: str) -> None:
    """Record one allocation.  ``lifetime="leaf"`` registers a
    finalizer per minted leaf (released as the buffers die);
    ``"owner"`` pins the charge until the owner dies — a SAME-SITE
    owner-lifetime allocation replaces the previous one (a rebuilt
    pool must not double-count)."""
    import jax

    aid = next(_AIDS)
    label = site if lifetime == "owner" else f"{site}#{aid}"
    leaves = []
    if lifetime == "leaf":
        leaves = [leaf for leaf in jax.tree_util.tree_leaves(result)
                  if getattr(leaf, "shape", None) is not None]
    with _STATE_LOCK:
        _drain_releases_locked()
        ledger = _LEDGERS.setdefault(tok, _OwnerLedger())
        allocs = ledger.pools.setdefault(pool, {})
        if lifetime == "owner":
            for old_aid in [k for k, a in allocs.items()
                            if a.label == label]:
                del allocs[old_aid]
        allocs[aid] = _Alloc(label=label, nbytes=nbytes,
                             leaves_left=len(leaves) or 1)
        live = sum(a.nbytes for a in allocs.values())
        ledger.peak[pool] = max(ledger.peak.get(pool, 0), live)
    if lifetime == "leaf":
        import numpy as np

        for leaf in leaves:
            lb = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            try:
                weakref.finalize(leaf, _release, tok, pool, aid, lb)
            except TypeError:      # pragma: no cover - exotic leaf type
                pass


# -- the armed wrapper -----------------------------------------------------


def _sig_entry(x) -> object:
    """Hashable size-determining key for one allocator argument: array
    leaves key by (shape, dtype) — two calls with the same signature
    mint the same bytes, which is exactly what the projection memo
    needs."""
    if x is None or type(x) in (bool, int, float, str, bytes):
        return ("v", x)
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    import jax

    try:
        leaves, treedef = jax.tree_util.tree_flatten(x)
        if len(leaves) == 1 and leaves[0] is x:
            # Unregistered object: its own pytree leaf — recursing
            # would never terminate.
            return ("obj", type(x).__name__)
        return (str(treedef),
                tuple(_sig_entry(leaf) for leaf in leaves))
    except Exception:              # noqa: BLE001 - opaque arg
        return ("obj", type(x).__name__)


def _signature(args, kwargs, method: bool) -> tuple:
    sig = [_sig_entry(a) for a in (args[1:] if method else args)]
    for k in sorted(kwargs):
        sig.append((k, _sig_entry(kwargs[k])))
    return tuple(sig)


def _resolve(value, args, kwargs):
    return value(*args, **kwargs) if callable(value) else value


def _default_site(fn) -> str:
    mod = getattr(fn, "__module__", "") or ""
    qual = getattr(fn, "__qualname__", None) or getattr(
        fn, "__name__", None) or repr(fn)
    return f"{mod.rsplit('.', 1)[-1]}.{qual}"


def _wrap(fn, spec: PoolSpec):
    import functools

    site = spec.site

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _vetoed() or getattr(_IN_ALLOC, "depth", 0):
            # Vetoed live, or a nested annotated allocator under an
            # outer one (``_admission_cache_1`` → ``_fresh_cache``):
            # the OUTERMOST call owns the charge.
            return fn(*args, **kwargs)
        owner = args[0] if spec.method and args else None
        tok = _owner_token(owner)
        pool = str(_resolve(spec.pool, args, kwargs))
        lifetime = str(_resolve(spec.lifetime, args, kwargs))
        budget = (spec.budget_fn(*args, **kwargs)
                  if spec.budget_fn is not None else spec.budget_bytes)
        sig = _signature(args, kwargs, spec.method)
        _IN_ALLOC.depth = 1
        try:
            # Projected bytes BEFORE the allocation: the spec's
            # project_fn (the engine's memoized cache eval_shape) or
            # the memo of a previous identical signature.  The first
            # call of an unprojectable site charges after the fact —
            # still ahead of the cumulative OOM.
            projected = _PROJ.get((site, tok, sig))
            if projected is None and spec.project_fn is not None:
                try:
                    projected = int(spec.project_fn(*args, **kwargs))
                except Exception:  # noqa: BLE001 - projection must
                    projected = None  # never break the allocator
            if projected is not None and budget is not None:
                live = _live_tok(tok)
                if lifetime == "owner":
                    # A rebuild replaces the previous same-site
                    # charge — check the budget against the NET.
                    live -= _replaceable_bytes(tok, pool, site)
                if live + projected > budget:
                    raise _budget_error(site, pool, projected, budget,
                                        tok)
            span = events.span("memory/" + pool, pool=pool, site=site,
                               bytes=int(projected or 0), live=0,
                               budget=int(budget or 0))
            with span:
                result = fn(*args, **kwargs)
                actual = (projected if projected is not None
                          else tree_bytes(result))
                _PROJ.setdefault((site, tok, sig), actual)
                _charge(tok, pool, site, actual, result, lifetime)
                live = _live_tok(tok)
                # The span records at exit: fill in what the
                # allocation actually cost and where the pool landed.
                attrs = getattr(span, "_attrs", None)
                if attrs is not None:
                    attrs["bytes"] = int(actual)
                    attrs["live"] = int(live)
            if budget is not None:
                if live > budget:
                    # Unprojectable first call that overran: the
                    # charge stands (the buffers exist), the error
                    # surfaces NOW — before the next allocation and
                    # long before an opaque XLA OOM.
                    raise _budget_error(site, pool, actual, budget,
                                        tok)
                if live > 0.9 * budget:
                    events.instant("memory/near_miss", pool=pool,
                                   site=site, live=int(live),
                                   budget=int(budget))
            return result
        finally:
            _IN_ALLOC.depth = 0

    wrapper.__ttd_memory_pool__ = spec.pool
    wrapper.__ttd_memcheck_wrapped__ = True
    return wrapper


def track(owner, pool: str, tree, label: str,
          budget: Optional[int] = None) -> None:
    """Explicitly charge a STORED tree (the preload prefix pairs: held
    as minted, copied per admission, freed at LRU eviction — exactly
    the leaf-lifetime contract).  No-op unless the sanitizer is armed.
    Raises ``MemoryBudgetError`` when the charge lands over ``budget``
    (the store already happened; the error stops the leak's growth)."""
    if not armed() or _vetoed():
        return
    tok = _owner_token(owner)
    nbytes = tree_bytes(tree)
    _charge(tok, pool, f"track:{label}", nbytes, tree, "leaf")
    events.instant("memory/" + pool, pool=pool, site=f"track:{label}",
                   bytes=int(nbytes),
                   live=int(live_bytes(owner=owner, pool=pool)))
    if budget is not None and live_bytes(owner=owner) > budget:
        raise _budget_error("track:" + label, pool, nbytes, budget, tok)


def annotate(fn, *, pool, budget_bytes=None, budget_fn=None,
             project_fn=None, lifetime="owner",
             site: Optional[str] = None):
    """Implementation of ``registry.memory_budget`` (deferred there to
    keep the registry import-light)."""
    import inspect

    name = site or _default_site(fn)
    try:
        params = list(inspect.signature(fn).parameters)
    except (ValueError, TypeError):    # pragma: no cover - C callables
        params = []
    spec = register_site(PoolSpec(
        site=name, pool=pool, budget_bytes=budget_bytes,
        budget_fn=budget_fn, project_fn=project_fn, lifetime=lifetime,
        method=bool(params) and params[0] in ("self", "cls")))
    try:
        fn.__ttd_memory_pool__ = pool
    except (AttributeError, TypeError):  # pragma: no cover
        pass
    if not armed():
        return fn
    return _wrap(fn, spec)


# -- static checker --------------------------------------------------------

#: Host-side device-allocation calls the hot-module rule audits (numpy
#: allocations are host memory; ``jnp.asarray`` of small host lists is
#: table/mask plumbing, deliberately out of scope).
_ALLOC_CALLS = {"jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty",
                "jax.device_put", "device_put"}

#: Files that MUST declare at least one ``@memory_budget`` pool — the
#: big-allocator modules the ROADMAP names (full package-relative
#: paths: a tools/bench_serving.py must not match serving.py's rule).
_REQUIRED_HOT = (
    os.path.join("tensorflow_train_distributed_tpu", "serving.py"),
    os.path.join("tensorflow_train_distributed_tpu", "training",
                 "trainer.py"),
)


def _has_decorator(fn, name: str) -> Optional[ast.expr]:
    for dec in fn.decorator_list:
        dname = _decorator_name(dec)
        if dname and dname.split(".")[-1] == name:
            return dec
    return None


def _kwarg(call: Optional[ast.expr], name: str) -> Optional[ast.expr]:
    if not isinstance(call, ast.Call):
        return None
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _literal_ints(node: Optional[ast.expr]) -> Optional[tuple]:
    if node is None:
        return ()
    elts = (node.elts if isinstance(node, (ast.Tuple, ast.List))
            else [node])
    out = []
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.append(e.value)
        else:
            return None
    return tuple(out)


def _is_alloc_call(node: ast.Call) -> bool:
    name = _dotted(node.func) or ""
    short = name.split(".")[-1]
    return (name in _ALLOC_CALLS
            or (short in ("zeros", "ones", "full", "empty")
                and name.startswith(("jnp.", "jax.numpy."))))


def _func_defs(tree: ast.Module) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _called_names(fn: ast.FunctionDef) -> set:
    """Names ``fn``'s body calls directly (``helper(...)``) or through
    an instance (``self.helper(...)``) — the intra-module sanction
    closure's edges."""
    out = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            out.add(f.id)
        elif isinstance(f, ast.Attribute):
            out.add(f.attr)
    return out


def _sanctioned_functions(tree: ast.Module) -> set:
    """FunctionDef nodes (by id) whose device allocations are owned:
    ``@memory_budget`` allocators, jit programs, ``eval_shape``
    thunks, and everything those reach through intra-module calls —
    nested defs inherit their enclosing def's sanction."""
    defs = _func_defs(tree)
    by_name: Dict[str, List[ast.FunctionDef]] = {}
    for d in defs:
        by_name.setdefault(d.name, []).append(d)
    seeds = set()
    eval_shape_args = set()
    seam_args = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            if name.split(".")[-1] == "eval_shape":
                for a in node.args:
                    if isinstance(a, ast.Name):
                        eval_shape_args.add(a.id)
            if name.endswith("compilecheck.jit") or name == "jit":
                if node.args and isinstance(node.args[0], ast.Name):
                    seam_args.add(node.args[0].id)
    for d in defs:
        if (_has_decorator(d, "memory_budget") is not None
                or _has_decorator(d, "compile_site") is not None
                or _is_jit_decorated(d)
                or d.name in eval_shape_args
                or d.name in seam_args):
            seeds.add(id(d))
    # Nested defs inherit; calls propagate (fixpoint over names).
    parents: Dict[int, Optional[int]] = {}
    for d in defs:
        for child in ast.walk(d):
            if child is not d and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parents.setdefault(id(child), id(d))
    sanctioned = set(seeds)
    changed = True
    while changed:
        changed = False
        for d in defs:
            if id(d) in sanctioned:
                continue
            p = parents.get(id(d))
            if p is not None and p in sanctioned:
                sanctioned.add(id(d))
                changed = True
        for d in defs:
            if id(d) not in sanctioned:
                continue
            for callee in _called_names(d):
                for target in by_name.get(callee, ()):
                    if id(target) not in sanctioned:
                        sanctioned.add(id(target))
                        changed = True
    return sanctioned


def _enclosing_chain(tree: ast.Module) -> Dict[int, List[ast.AST]]:
    """node id -> chain of enclosing FunctionDefs (innermost last)."""
    chains: Dict[int, List[ast.AST]] = {}

    def visit(node, chain):
        for child in ast.iter_child_nodes(node):
            nchain = chain
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                nchain = chain + [child]
            chains[id(child)] = nchain
            visit(child, nchain)

    chains[id(tree)] = []
    visit(tree, [])
    return chains


def _unbudgeted_alloc_findings(tree: ast.Module,
                               path: str) -> List[Finding]:
    sanctioned = _sanctioned_functions(tree)
    chains = _enclosing_chain(tree)
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_alloc_call(node)):
            continue
        chain = chains.get(id(node), [])
        if any(id(d) in sanctioned for d in chain):
            continue
        where = chain[-1].name if chain else "<module scope>"
        out.append(Finding(
            CHECKER, path, node.lineno,
            f"un-annotated device allocation: "
            f"{_dotted(node.func)}(...) in '{where}' is not reachable "
            f"from any @memory_budget allocator, jit program, or "
            f"eval_shape thunk — declare the pool it belongs to "
            f"(runtime/lint/registry.memory_budget) so the HBM "
            f"sanitizer and ttd_engine_hbm_bytes can see it"))
    return out


def _expr_path(node) -> Optional[str]:
    """Dotted source form of a Name/Attribute chain (``self._cache``),
    None for anything the alias rule cannot compare."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_path(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _donating_programs(tree: ast.Module) -> Dict[str, tuple]:
    """name -> (donated argnums, is_method) for every
    ``@compile_site(donates=...)`` function in the module."""
    out: Dict[str, tuple] = {}
    for d in _func_defs(tree):
        dec = _has_decorator(d, "compile_site")
        if dec is None:
            continue
        donates = _literal_ints(_kwarg(dec, "donates"))
        if not donates:
            continue
        args = d.args.posonlyargs + d.args.args
        is_method = bool(args) and args[0].arg in ("self", "cls")
        out[d.name] = (donates, is_method)
    return out


def _assign_targets(stmt) -> set:
    """Dotted paths a statement rebinds (Assign targets, tuple
    elements included)."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out = set()
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                p = _expr_path(e)
                if p:
                    out.add(p)
        else:
            p = _expr_path(t)
            if p:
                out.add(p)
    return out


def _donation_alias_findings(tree: ast.Module,
                             path: str) -> List[Finding]:
    """Call-site audit of declared donations: a donated argument that
    is a bare name/attribute and is NOT rebound by the same statement
    (and not returned) stays live behind the donation — XLA keeps both
    buffers and peak HBM doubles."""
    programs = _donating_programs(tree)
    if not programs:
        return []
    out: List[Finding] = []
    for stmt in ast.walk(tree):
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.Expr,
                                 ast.Return)):
            continue
        value = getattr(stmt, "value", None)
        if not isinstance(value, ast.Call):
            continue
        f = value.func
        callee = None
        shift = 0
        if isinstance(f, ast.Name) and f.id in programs:
            callee = f.id
        elif (isinstance(f, ast.Attribute) and f.attr in programs
                and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")):
            callee = f.attr
            shift = 1 if programs[f.attr][1] else 0
        if callee is None:
            continue
        if isinstance(stmt, ast.Return):
            continue               # ownership transfers to the caller
        donates, _ = programs[callee]
        rebound = _assign_targets(stmt)
        arg_paths = [_expr_path(a) for a in value.args]
        for argnum in donates:
            idx = argnum - shift
            if not 0 <= idx < len(arg_paths):
                continue
            p = arg_paths[idx]
            if p is None:
                continue
            # Aliasing inside the call: the same buffer donated AND
            # passed live in another position.
            if arg_paths.count(p) > 1:
                out.append(Finding(
                    CHECKER, path, stmt.lineno,
                    f"donation-defeating alias: '{p}' is passed to "
                    f"'{callee}' both in donated position {argnum} "
                    f"and again un-donated — XLA cannot reuse the "
                    f"buffer and peak HBM doubles"))
                continue
            if "." in p and p not in rebound:
                out.append(Finding(
                    CHECKER, path, stmt.lineno,
                    f"donation-defeating alias: '{p}' is donated to "
                    f"'{callee}' (donates={tuple(donates)}) but stays "
                    f"bound after the call — rebind it from the "
                    f"result ('{p} = ...') or the donation is "
                    f"defeated and peak HBM silently doubles"))
    return out


def _declaration_findings(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for d in _func_defs(tree):
        dec = _has_decorator(d, "memory_budget")
        if dec is None:
            continue
        if (_kwarg(dec, "budget_bytes") is None
                and _kwarg(dec, "budget_fn") is None):
            out.append(Finding(
                CHECKER, path, d.lineno,
                f"'{d.name}': @memory_budget declares a pool but no "
                f"budget — add budget_bytes=... or budget_fn=... (a "
                f"pool without a budget is a gauge, not a "
                f"discipline; a budget_fn may return None to "
                f"track-only at runtime, but the declaration must "
                f"say so)"))
        if _kwarg(dec, "pool") is None:
            out.append(Finding(
                CHECKER, path, d.lineno,
                f"'{d.name}': @memory_budget without pool=... — the "
                f"ledger, the gauges, and the trace spans all key on "
                f"the pool name"))
    return out


def _module_is_hot(tree: ast.Module) -> bool:
    return any(_has_decorator(d, "memory_budget") is not None
               for d in _func_defs(tree))


@register_checker(CHECKER)
def check(tree: ast.Module, lines, path: str, ctx) -> List[Finding]:
    findings: List[Finding] = []
    hot = _module_is_hot(tree)
    required = any(path.endswith(req) for req in _REQUIRED_HOT)
    if required and not hot:
        findings.append(Finding(
            CHECKER, path, 1,
            "registered hot allocator module declares no "
            "@memory_budget pool — the big device allocators here "
            "must be budget-annotated (see README 'Memory "
            "discipline')"))
    if hot:
        findings.extend(_unbudgeted_alloc_findings(tree, path))
        findings.extend(_declaration_findings(tree, path))
    # The donation-alias audit applies wherever donating programs are
    # declared (compile_site's donates literal is the contract).
    findings.extend(_donation_alias_findings(tree, path))
    return findings
