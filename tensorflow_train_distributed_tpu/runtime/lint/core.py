"""ttd-lint core: findings, suppressions, file walking, the runner.

Checkers are functions ``(tree, source_lines, path, ctx) -> [Finding]``
registered in ``CHECKERS``; ``run_lint`` parses each file once and
fans it to every requested checker, then drops findings suppressed by
the one shared suppression format:

    some_code()            # ttd-lint: disable=concurrency
    other_code()           # ttd-lint: disable=concurrency,dispatch

A suppression names the checker it silences (never a bare
``disable``), so grepping for a checker's name finds every place it
was overridden — the suppression IS documentation.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Sequence

_SUPPRESS_RE = re.compile(r"#\s*ttd-lint:\s*disable=([a-z0-9_,\- ]+)")

# Directories never linted (fixtures PLANT bugs for the checkers'
# own mutation tests; caches are noise).
_SKIP_DIRS = {"__pycache__", "lint_fixtures", ".git"}


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    path: str
    line: int
    message: str

    def format(self, root: Optional[str] = None) -> str:
        path = (os.path.relpath(self.path, root)
                if root else self.path)
        return f"{path}:{self.line}: [{self.checker}] {self.message}"


def _suppressed(lines: Sequence[str], lineno: int, checker: str) -> bool:
    if not 1 <= lineno <= len(lines):
        return False
    m = _SUPPRESS_RE.search(lines[lineno - 1])
    if not m:
        return False
    names = {n.strip() for n in m.group(1).split(",")}
    return checker in names


def iter_source_files(paths: Sequence[str]) -> List[str]:
    """Every ``.py`` under the given files/dirs (sorted, skip-listed
    dirs pruned)."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for f in filenames:
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(set(out))


class LintContext:
    """Cross-file state checkers may need (repo root for README/tests
    lookups; lazily-read shared docs)."""

    def __init__(self, root: Optional[str] = None):
        if root is None:
            # runtime/lint/core.py -> repo root is four levels up.
            root = os.path.abspath(os.path.join(
                os.path.dirname(__file__), "..", "..", ".."))
        self.root = root
        self._docs: Dict[str, str] = {}

    def read_doc(self, relpath: str) -> str:
        if relpath not in self._docs:
            full = os.path.join(self.root, relpath)
            try:
                with open(full, encoding="utf-8") as f:
                    self._docs[relpath] = f.read()
            except OSError:
                self._docs[relpath] = ""
        return self._docs[relpath]

    def tests_corpus(self) -> str:
        """Concatenated test sources (the kill-switch checker's
        "exercised by at least one test" evidence), fixtures included
        — a fixture exercising a flag counts, linting fixtures for
        PLANTED bugs is what's excluded."""
        key = "<tests>"
        if key not in self._docs:
            tests_dir = os.path.join(self.root, "tests")
            chunks = []
            for dirpath, dirnames, filenames in os.walk(tests_dir):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        try:
                            with open(os.path.join(dirpath, f),
                                      encoding="utf-8") as fh:
                                chunks.append(fh.read())
                        except OSError:
                            pass
            self._docs[key] = "\n".join(chunks)
        return self._docs[key]


# name -> checker fn; populated by the checker modules at import.
CHECKERS: Dict[str, Callable] = {}


def register_checker(name: str):
    def deco(fn):
        CHECKERS[name] = fn
        return fn
    return deco


def _load_checkers() -> None:
    # Imported lazily so ``import runtime.lint.core`` alone stays
    # dependency-free; each module registers itself.
    from tensorflow_train_distributed_tpu.runtime.lint import (  # noqa: F401
        concurrency,
        dispatch,
        flags,
        prometheus,
    )


def run_lint(paths: Optional[Sequence[str]] = None,
             checkers: Optional[Sequence[str]] = None,
             root: Optional[str] = None) -> List[Finding]:
    """Run the requested checkers (default: all) over ``paths``
    (default: the package + tools), dropping suppressed findings."""
    _load_checkers()
    ctx = LintContext(root)
    if paths is None:
        paths = [os.path.join(ctx.root, "tensorflow_train_distributed_tpu"),
                 os.path.join(ctx.root, "tools")]
    if checkers is None:
        names = sorted(CHECKERS)
    else:
        unknown = [c for c in checkers if c not in CHECKERS]
        if unknown:
            raise ValueError(f"unknown checker(s) {unknown}; "
                             f"known: {sorted(CHECKERS)}")
        names = list(checkers)
    findings: List[Finding] = []
    for path in iter_source_files(list(paths)):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding("io", path, 0, f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "syntax", path, e.lineno or 0, f"syntax error: {e.msg}"))
            continue
        lines = source.splitlines()
        for name in names:
            for f_ in CHECKERS[name](tree, lines, path, ctx):
                if not _suppressed(lines, f_.line, f_.checker):
                    findings.append(f_)
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings
