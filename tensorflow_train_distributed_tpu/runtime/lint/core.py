"""ttd-lint core: findings, suppressions, file walking, the runner.

Checkers are functions ``(tree, source_lines, path, ctx) -> [Finding]``
registered in ``CHECKERS``; ``run_lint`` parses each file once and
fans it to every requested checker, then drops findings suppressed by
the one shared suppression format:

    some_code()    # ttd-lint: disable=concurrency -- scrape is read-only
    other_code()   # ttd-lint: disable=concurrency,dispatch -- bench path

A suppression names the checker it silences (never a bare
``disable``) AND carries a trailing ``-- <why>`` reason, so grepping
for a checker's name finds every place it was overridden — the
suppression IS documentation, and the reason is its body.  The
framework lints the linter's own escape hatch: a suppression without
a reason, and a suppression that silenced nothing in this run (an
*unused* suppression — the hazard it excused is gone, or the comment
drifted off its line), are both reported as ``suppression`` findings.
Only suppressions naming a checker that actually ran are audited, so
``--checker``-scoped runs never flag another checker's suppressions.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*ttd-lint:\s*disable=([a-z0-9_,\- ]+?)(?:\s+--\s*(\S.*))?$")

# Directories never linted (fixtures PLANT bugs for the checkers'
# own mutation tests; caches are noise).
_SKIP_DIRS = {"__pycache__", "lint_fixtures", ".git"}


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    path: str
    line: int
    message: str

    def format(self, root: Optional[str] = None) -> str:
        path = (os.path.relpath(self.path, root)
                if root else self.path)
        return f"{path}:{self.line}: [{self.checker}] {self.message}"


def _parse_suppression(text: str) -> Optional[Tuple[Set[str], Optional[str]]]:
    """``(checker_names, reason_or_None)`` for a suppression comment,
    None when ``text`` carries no suppression at all."""
    m = _SUPPRESS_RE.search(text)
    if not m:
        return None
    names = {n.strip() for n in m.group(1).split(",") if n.strip()}
    reason = m.group(2)
    return names, (reason.strip() if reason else None)


def _suppressed(lines: Sequence[str], lineno: int, checker: str) -> bool:
    if not 1 <= lineno <= len(lines):
        return False
    parsed = _parse_suppression(lines[lineno - 1])
    return parsed is not None and checker in parsed[0]


def _iter_suppression_comments(
        source: str) -> Iterator[Tuple[int, Set[str], Optional[str]]]:
    """``(lineno, checker_names, reason)`` for every REAL suppression
    comment — tokenized, so docstring examples of the format (this very
    module's, for one) are not mistaken for live suppressions."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            parsed = _parse_suppression(tok.string)
            if parsed is not None:
                yield tok.start[0], parsed[0], parsed[1]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


#: Stable per-checker exit-code bits for the CLI (OR'd together, so a
#: machine caller can tell WHICH disciplines failed from the code alone;
#: ``--json`` carries the same map in-band).  0 = clean, 2 = usage
#: error (argparse convention, below every checker bit), 1 = findings
#: from an unregistered source (io/syntax).  Bits past 128 (memcheck
#: was the seventh checker; process statuses are 8-bit) cannot survive
#: the exit-status truncation, so ``exit_code`` folds them into the
#: generic bit 1 — the status stays nonzero and names what it can,
#: ``--json``'s ``exit_bits``/``counts`` carry the exact story.
CHECKER_EXIT_BITS: Dict[str, int] = {
    "concurrency": 4,
    "dispatch": 8,
    "kill-switch": 16,
    "prometheus": 32,
    "compilecheck": 64,
    "suppression": 128,
    "memcheck": 256,
}


def exit_code(findings: Sequence["Finding"]) -> int:
    """The CLI exit status for a finding list: OR of each finding
    checker's stable bit (1 for io/syntax), 0 when clean.  Bits past
    the 8-bit process-status range fold into bit 1 (a memcheck-only
    run exits 1, never a false 0 — the shell truncates 256 to 0)."""
    code = 0
    for f in findings:
        code |= CHECKER_EXIT_BITS.get(f.checker, 1)
    if code > 255:
        code = (code & 0xFF) | 1
    return code


def iter_source_files(paths: Sequence[str]) -> List[str]:
    """Every ``.py`` under the given files/dirs (sorted, skip-listed
    dirs pruned)."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for f in filenames:
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(set(out))


class LintContext:
    """Cross-file state checkers may need (repo root for README/tests
    lookups; lazily-read shared docs)."""

    def __init__(self, root: Optional[str] = None):
        if root is None:
            # runtime/lint/core.py -> repo root is four levels up.
            root = os.path.abspath(os.path.join(
                os.path.dirname(__file__), "..", "..", ".."))
        self.root = root
        self._docs: Dict[str, str] = {}

    def read_doc(self, relpath: str) -> str:
        if relpath not in self._docs:
            full = os.path.join(self.root, relpath)
            try:
                with open(full, encoding="utf-8") as f:
                    self._docs[relpath] = f.read()
            except OSError:
                self._docs[relpath] = ""
        return self._docs[relpath]

    def tests_corpus(self) -> str:
        """Concatenated test sources (the kill-switch checker's
        "exercised by at least one test" evidence), fixtures included
        — a fixture exercising a flag counts, linting fixtures for
        PLANTED bugs is what's excluded."""
        key = "<tests>"
        if key not in self._docs:
            tests_dir = os.path.join(self.root, "tests")
            chunks = []
            for dirpath, dirnames, filenames in os.walk(tests_dir):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        try:
                            with open(os.path.join(dirpath, f),
                                      encoding="utf-8") as fh:
                                chunks.append(fh.read())
                        except OSError:
                            pass
            self._docs[key] = "\n".join(chunks)
        return self._docs[key]


# name -> checker fn; populated by the checker modules at import.
CHECKERS: Dict[str, Callable] = {}


def register_checker(name: str):
    def deco(fn):
        CHECKERS[name] = fn
        return fn
    return deco


def _load_checkers() -> None:
    # Imported lazily so ``import runtime.lint.core`` alone stays
    # dependency-free; each module registers itself.
    from tensorflow_train_distributed_tpu.runtime.lint import (  # noqa: F401
        compilecheck,
        concurrency,
        dispatch,
        flags,
        memcheck,
        prometheus,
    )


def run_lint(paths: Optional[Sequence[str]] = None,
             checkers: Optional[Sequence[str]] = None,
             root: Optional[str] = None) -> List[Finding]:
    """Run the requested checkers (default: all) over ``paths``
    (default: the package + tools), dropping suppressed findings."""
    _load_checkers()
    ctx = LintContext(root)
    if paths is None:
        paths = [os.path.join(ctx.root, "tensorflow_train_distributed_tpu"),
                 os.path.join(ctx.root, "tools")]
    if checkers is None:
        names = sorted(CHECKERS)
    else:
        unknown = [c for c in checkers if c not in CHECKERS]
        if unknown:
            raise ValueError(f"unknown checker(s) {unknown}; "
                             f"known: {sorted(CHECKERS)}")
        names = list(checkers)
    findings: List[Finding] = []
    for path in iter_source_files(list(paths)):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding("io", path, 0, f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "syntax", path, e.lineno or 0, f"syntax error: {e.msg}"))
            continue
        lines = source.splitlines()
        used: set = set()          # (lineno, checker) actually silenced
        for name in names:
            for f_ in CHECKERS[name](tree, lines, path, ctx):
                if _suppressed(lines, f_.line, f_.checker):
                    used.add((f_.line, f_.checker))
                else:
                    findings.append(f_)
        # Lint the linter's escape hatch: reasons are mandatory, and a
        # suppression that silenced nothing (for a checker that RAN) is
        # dead weight hiding a fixed hazard — report both.
        ran = set(names)
        for lineno, sup_names, reason in _iter_suppression_comments(source):
            active = sorted(sup_names & ran)
            if not active:
                continue
            if reason is None:
                findings.append(Finding(
                    "suppression", path, lineno,
                    "suppression missing a reason: write '# ttd-lint: "
                    "disable=<checker> -- <why>'"))
            for c in active:
                if (lineno, c) not in used:
                    findings.append(Finding(
                        "suppression", path, lineno,
                        f"unused suppression for checker '{c}' (no "
                        f"finding was silenced on this line — remove "
                        f"it, or re-anchor it to the hazard)"))
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings
