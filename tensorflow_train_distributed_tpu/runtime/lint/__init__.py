"""ttd-lint: static concurrency/purity analysis + runtime lock sanitizer.

The correctness discipline of this codebase, turned from reviewer
vigilance into a mechanically-enforced pass (the TF-Replicator lesson:
replica orchestration lives or dies on enforced invariants).  Two
halves share one annotation registry (``registry``):

- **static checkers** (``python -m tools.ttd_lint``, and the tier-1
  test that runs them over the whole package):

  - ``concurrency`` — classes declare which lock guards which shared
    attribute (``_GUARDED_BY``) and which thread role(s) each entry
    point runs on (``@thread_role``); the checker walks each class's
    call graph and flags any guarded-attribute access on a path where
    the owning lock is not provably held (the exact bug class of the
    PR 6/7 review-pass fixes);
  - ``dispatch`` — host-sync hazards inside ``@dispatch_critical``
    functions (the overlap-critical decode window) and Python-time
    nondeterminism / host syncs inside jitted functions;
  - ``flags`` — every ``TTD_*`` kill switch referenced anywhere must
    be documented in README and exercised by at least one test;
  - ``prometheus`` — metric naming conventions (counters ``_total``,
    histograms ``_seconds``) and README coverage for every ``ttd_*``
    metric name, unified from the old ad-hoc test lint;
  - ``compilecheck`` — every ``jax.jit`` site must declare its compile
    discipline with ``@compile_site(buckets=..., donates=...)`` (or
    route through ``compilecheck.jit``), the declared donation/statics
    must match the jit kwargs, and call sites must not feed raw
    host-measured sizes (``len``/``.shape``) or python-scalar closures
    across the boundary un-bucketed;
  - ``memcheck`` — the big device allocators declare their HBM pool
    and budget with ``@memory_budget(pool=..., budget_bytes=...)``;
    in an annotated (hot) module every host-side device allocation
    must be reachable from an annotated allocator / jit program /
    eval_shape thunk, and call sites of donating ``@compile_site``
    programs must rebind the donated buffer (a kept alias silently
    doubles peak HBM).

- **runtime sanitizers**: ``TTD_LOCKCHECK=1`` (``lockcheck``) wraps
  the package's locks with an acquisition-order graph that raises on
  cycles (potential deadlock) and arms per-attribute guards that raise
  on guarded access without the declared lock; ``TTD_COMPILECHECK=1``
  (``compilecheck``) wraps the annotated jit sites with per-callsite
  compile tracking that raises ``RecompileError`` past a site's
  declared budget, emits ``compile/<site>`` flight-recorder spans, and
  feeds ``ttd_engine_compiles_total``; ``TTD_MEMCHECK=1``
  (``memcheck``) tracks live bytes per declared pool, raises
  ``MemoryBudgetError`` before an over-budget allocation with the
  offending allocation diffed against the live set, emits
  ``memory/<pool>`` spans, and feeds the labeled
  ``ttd_engine_hbm_bytes{pool=...}`` gauge family.  conftest arms all
  three for tier-1, so every existing test doubles as a race test, a
  recompile-storm test, and a memory-budget test.
  ``TTD_NO_LOCKCHECK=1`` / ``TTD_NO_COMPILECHECK=1`` /
  ``TTD_NO_MEMCHECK=1`` are the escape hatches.

One suppression format everywhere: ``# ttd-lint: disable=<checker> --
<why>`` on the offending line (comma-separate several checkers).  The
reason is mandatory and unused suppressions are themselves findings —
the framework lints its own escape hatch.
"""

from tensorflow_train_distributed_tpu.runtime.lint.core import (  # noqa: F401
    CHECKER_EXIT_BITS,
    Finding,
    exit_code,
    iter_source_files,
    run_lint,
)
from tensorflow_train_distributed_tpu.runtime.lint.registry import (  # noqa: F401
    THREAD_ROLES,
    compile_site,
    concurrency_guarded,
    current_role,
    dispatch_critical,
    locks_held,
    memory_budget,
    thread_role,
)
