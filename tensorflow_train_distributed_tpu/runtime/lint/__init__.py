"""ttd-lint: static concurrency/purity analysis + runtime lock sanitizer.

The correctness discipline of this codebase, turned from reviewer
vigilance into a mechanically-enforced pass (the TF-Replicator lesson:
replica orchestration lives or dies on enforced invariants).  Two
halves share one annotation registry (``registry``):

- **static checkers** (``python -m tools.ttd_lint``, and the tier-1
  test that runs them over the whole package):

  - ``concurrency`` — classes declare which lock guards which shared
    attribute (``_GUARDED_BY``) and which thread role(s) each entry
    point runs on (``@thread_role``); the checker walks each class's
    call graph and flags any guarded-attribute access on a path where
    the owning lock is not provably held (the exact bug class of the
    PR 6/7 review-pass fixes);
  - ``dispatch`` — host-sync hazards inside ``@dispatch_critical``
    functions (the overlap-critical decode window) and Python-time
    nondeterminism / host syncs inside jitted functions;
  - ``flags`` — every ``TTD_*`` kill switch referenced anywhere must
    be documented in README and exercised by at least one test;
  - ``prometheus`` — metric naming conventions (counters ``_total``,
    histograms ``_seconds``) and README coverage for every ``ttd_*``
    metric name, unified from the old ad-hoc test lint.

- **runtime sanitizer** (``lockcheck``): ``TTD_LOCKCHECK=1`` wraps the
  package's locks with an acquisition-order graph that raises on
  cycles (potential deadlock) and arms per-attribute guards that raise
  on guarded access without the declared lock — conftest arms it for
  tier-1, so every existing gateway/replica/chaos test doubles as a
  race test.  ``TTD_NO_LOCKCHECK=1`` is the escape hatch.

One suppression format everywhere: ``# ttd-lint: disable=<checker>``
on the offending line (comma-separate several checkers).
"""

from tensorflow_train_distributed_tpu.runtime.lint.core import (  # noqa: F401
    Finding,
    iter_source_files,
    run_lint,
)
from tensorflow_train_distributed_tpu.runtime.lint.registry import (  # noqa: F401
    THREAD_ROLES,
    concurrency_guarded,
    current_role,
    dispatch_critical,
    locks_held,
    thread_role,
)
