"""Thread-role concurrency checker.

The static half of the concurrency discipline: a class that declares
``_GUARDED_BY`` (attribute -> guard spec, see ``registry``) gets its
methods analyzed as a call graph seeded from ``@thread_role`` entry
points, and every access to a guarded attribute is checked against the
rule its spec declares:

- ``("_lock",)``            every access must hold ``self._lock``;
- ``("_lock", "driver")``   WRITES must hold the lock; lock-free READS
                            are allowed only on paths provably confined
                            to the owner role(s) — the single-writer /
                            locked-reader pattern (e.g. the engine's
                            stats dicts: the driver loop reads its own
                            writes lock-free, scrape threads lock);
- ``(None, "watchdog")``    an atomic-publish attribute: no lock
                            exists, only the owner role(s) may WRITE,
                            single-field reads are free.

"Provably held" is lexical: a ``with self._lock:`` block, or a helper
declared ``@locks_held("_lock")`` (whose call sites are then checked
instead).  Role confinement is a fixpoint over the class's internal
call graph: a method's roles are its own ``@thread_role`` declaration
unioned with every caller's roles — so a helper reachable from both
the driver loop and a handler-thread entry point must lock, even
though the driver path alone would not need to.

This is exactly the bug class of the PR 6/7 review-pass fixes
(``_prefix_caches`` OrderedDict walks racing the driver's LRU
``move_to_end``; the replica pool's cross-thread maps): the checker
makes the next one a lint failure instead of a review catch.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tensorflow_train_distributed_tpu.runtime.lint.core import (
    Finding,
    register_checker,
)

CHECKER = "concurrency"

# Container-method calls that mutate the receiver (a
# ``self._admit.append(...)`` is a WRITE to ``_admit``).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "clear", "remove", "discard", "add",
    "update", "setdefault", "move_to_end", "sort", "reverse",
})


def _decorator_name(dec: ast.expr) -> Optional[str]:
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _decorator_str_args(dec: ast.expr) -> Tuple[str, ...]:
    if not isinstance(dec, ast.Call):
        return ()
    out = []
    for a in dec.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            out.append(a.value)
    return tuple(out)


def _parse_spec(attr: str, node: ast.expr):
    """AST mirror of ``registry._normalize_spec`` -> (lock, owners) or
    an error string."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, ()
    if isinstance(node, (ast.Tuple, ast.List)) and node.elts:
        first = node.elts[0]
        if not (isinstance(first, ast.Constant)
                and (first.value is None or isinstance(first.value, str))):
            return f"_GUARDED_BY[{attr!r}]: lock must be a str or None"
        lock = first.value
        owners = []
        for e in node.elts[1:]:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return f"_GUARDED_BY[{attr!r}]: owner roles must be strs"
            owners.append(e.value)
        if lock is None and not owners:
            return (f"_GUARDED_BY[{attr!r}]: a lockless attribute needs "
                    f"an owner role")
        return lock, tuple(owners)
    return (f"_GUARDED_BY[{attr!r}]: spec must be a string or a "
            f"non-empty tuple literal")


@dataclasses.dataclass
class _Access:
    attr: str
    line: int
    held: frozenset
    write: bool


@dataclasses.dataclass
class _Method:
    name: str
    line: int
    roles: Set[str]
    locks_held: Tuple[str, ...]
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    # (callee name, locks held at the call site, line)
    calls: List[Tuple[str, frozenset, int]] = dataclasses.field(
        default_factory=list)


class _MethodWalker:
    """One method's lexical walk: tracks the ``with self.<lock>:``
    nesting and records guarded-attribute accesses + self-calls."""

    def __init__(self, method: _Method, guarded: Set[str],
                 locks: Set[str]):
        self.m = method
        self.guarded = guarded
        self.locks = locks
        self._parents: Dict[int, ast.AST] = {}

    def run(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        base = frozenset(self.m.locks_held)
        for stmt in fn.body:
            self._walk(stmt, base)

    def _walk(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, ast.With):
            acquired = set()
            for item in node.items:
                ce = item.context_expr
                self._walk(ce, held)
                if (isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"
                        and ce.attr in self.locks):
                    acquired.add(ce.attr)
            inner = held | frozenset(acquired)
            for stmt in node.body:
                self._walk(stmt, inner)
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            if node.attr in self.guarded:
                self.m.accesses.append(_Access(
                    node.attr, node.lineno, held, self._is_write(node)))
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"):
                self.m.calls.append((f.attr, held, node.lineno))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)

    def _is_write(self, node: ast.Attribute) -> bool:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = self._parents.get(id(node))
        # self.attr[k] = v / del self.attr[k] / self.attr[k] += 1:
        # the Subscript target carries Store/Del.
        if (isinstance(parent, ast.Subscript) and parent.value is node
                and isinstance(parent.ctx, (ast.Store, ast.Del))):
            return True
        # self.attr.append(...) and friends.
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _MUTATORS):
            gp = self._parents.get(id(parent))
            if isinstance(gp, ast.Call) and gp.func is parent:
                return True
        return False


def _analyze_class(cls: ast.ClassDef, path: str) -> List[Finding]:
    findings: List[Finding] = []
    specs: Dict[str, Tuple[Optional[str], Tuple[str, ...]]] = {}
    spec_line = cls.lineno
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_GUARDED_BY"):
            spec_line = stmt.lineno
            if not isinstance(stmt.value, ast.Dict):
                findings.append(Finding(
                    CHECKER, path, stmt.lineno,
                    f"{cls.name}._GUARDED_BY must be a dict literal"))
                return findings
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    findings.append(Finding(
                        CHECKER, path, stmt.lineno,
                        f"{cls.name}._GUARDED_BY keys must be string "
                        f"attribute names"))
                    continue
                parsed = _parse_spec(k.value, v)
                if isinstance(parsed, str):
                    findings.append(Finding(CHECKER, path, stmt.lineno,
                                            f"{cls.name}: {parsed}"))
                    continue
                specs[k.value] = parsed
    if not specs:
        return findings
    guarded = set(specs)
    locks = {lock for lock, _ in specs.values() if lock is not None}

    methods: Dict[str, _Method] = {}
    for stmt in cls.body:
        if not isinstance(stmt, ast.FunctionDef):
            continue
        roles: Set[str] = set()
        held: Tuple[str, ...] = ()
        for dec in stmt.decorator_list:
            dn = _decorator_name(dec)
            if dn == "thread_role":
                roles.update(_decorator_str_args(dec))
            elif dn == "locks_held":
                held = held + _decorator_str_args(dec)
        m = _Method(stmt.name, stmt.lineno, roles, held)
        if stmt.name != "__init__":     # construction precedes sharing
            _MethodWalker(m, guarded, locks).run(stmt)
        methods[stmt.name] = m

    # Role fixpoint over the class-internal call graph: a callee runs
    # on every role any caller runs on.
    changed = True
    while changed:
        changed = False
        for m in methods.values():
            for callee, _, _ in m.calls:
                target = methods.get(callee)
                if target is not None and not m.roles <= target.roles:
                    target.roles |= m.roles
                    changed = True

    # locks_held call-site verification.
    for m in methods.values():
        for callee, held, line in m.calls:
            target = methods.get(callee)
            if target is None or not target.locks_held:
                continue
            missing = [lk for lk in target.locks_held if lk not in held]
            if missing:
                findings.append(Finding(
                    CHECKER, path, line,
                    f"{cls.name}.{m.name} calls {callee}() declared "
                    f"@locks_held({', '.join(map(repr, missing))}) "
                    f"without holding the lock(s)"))

    # Guarded-attribute access verification.
    for m in methods.values():
        for acc in m.accesses:
            lock, owners = specs[acc.attr]
            if lock is not None and lock in acc.held:
                continue
            role_confined = bool(m.roles) and m.roles <= set(owners)
            if acc.write:
                if lock is None and role_confined:
                    continue
                if lock is None:
                    what = (f"write to atomic-publish attribute "
                            f"'{acc.attr}' (owner role(s) "
                            f"{sorted(owners)}) on a path with role(s) "
                            f"{sorted(m.roles) or '<undeclared>'}")
                else:
                    what = (f"write to '{acc.attr}' without holding "
                            f"self.{lock}")
                findings.append(Finding(
                    CHECKER, path, acc.line,
                    f"{cls.name}.{m.name}: {what}"))
            else:
                if lock is None or role_confined:
                    continue
                reason = ("method has no declared or inherited thread "
                          "role" if not m.roles else
                          f"path runs on role(s) {sorted(m.roles)}, "
                          f"owner(s) {sorted(owners) or 'none'}")
                findings.append(Finding(
                    CHECKER, path, acc.line,
                    f"{cls.name}.{m.name}: read of '{acc.attr}' "
                    f"without holding self.{lock} ({reason})"))

    # Declared locks must exist somewhere in the class (a typo'd lock
    # name would silently never match a with-block).
    assigned_attrs = {
        t.attr
        for stmt in ast.walk(cls)
        for t in ast.walk(stmt)
        if isinstance(t, ast.Attribute)
        and isinstance(t.ctx, ast.Store)
        and isinstance(t.value, ast.Name) and t.value.id == "self"
    }
    for attr, (lock, _) in sorted(specs.items()):
        if lock is not None and lock not in assigned_attrs:
            findings.append(Finding(
                CHECKER, path, spec_line,
                f"{cls.name}: declared lock '{lock}' for '{attr}' is "
                f"never assigned on self"))
    return findings


@register_checker(CHECKER)
def check(tree: ast.Module, lines, path: str, ctx) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_analyze_class(node, path))
    return findings
