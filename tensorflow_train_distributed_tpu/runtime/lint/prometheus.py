"""Prometheus metric-conventions checker.

The ONE metrics lint (the old ad-hoc test in tests/test_gateway.py now
delegates here — one framework, one suppression format):

- every metric registered via ``*.counter("name", ...)`` /
  ``*.fn_counter`` must end in ``_total``;
- every ``*.histogram("name", ...)`` must end in ``_seconds``
  (latency histograms observe seconds; a byte/count histogram earns a
  suppression with its reason on the line);
- every ``ttd_*`` metric name registered anywhere must appear
  (backticked) in README's metric documentation — README is the
  single source of truth the scrape surface promises.

Checked statically from the registration call sites, so stub metrics
in tests and future registries (training-side, replica-side) are held
to the same rules without instantiating anything.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tensorflow_train_distributed_tpu.runtime.lint.core import (
    Finding,
    register_checker,
)

CHECKER = "prometheus"

_COUNTER_FNS = {"counter", "fn_counter"}
_HISTOGRAM_FNS = {"histogram"}
_GAUGE_FNS = {"gauge", "labeled_gauge"}
_ALL_FNS = _COUNTER_FNS | _HISTOGRAM_FNS | _GAUGE_FNS
# Constructor names double as registration sites (Counter("x", ...)).
_CTOR_MAP = {"Counter": "counter", "FnCounter": "fn_counter",
             "Histogram": "histogram", "Gauge": "gauge",
             "LabeledGauge": "labeled_gauge"}


def _metric_name(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


@register_checker(CHECKER)
def check(tree: ast.Module, lines, path: str, ctx) -> List[Finding]:
    readme = ctx.read_doc("README.md")
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        kind = None
        if isinstance(f, ast.Attribute) and f.attr in _ALL_FNS:
            kind = f.attr
        elif isinstance(f, ast.Name) and f.id in _CTOR_MAP:
            kind = _CTOR_MAP[f.id]
        if kind is None:
            continue
        name = _metric_name(node)
        if name is None:
            continue            # dynamic name: nothing to check
        if kind in _COUNTER_FNS and not name.endswith("_total"):
            findings.append(Finding(
                CHECKER, path, node.lineno,
                f"counter '{name}' must end in _total"))
        if kind in _HISTOGRAM_FNS and not name.endswith("_seconds"):
            findings.append(Finding(
                CHECKER, path, node.lineno,
                f"histogram '{name}' must end in _seconds"))
        if name.startswith("ttd_") and f"`{name}`" not in readme:
            findings.append(Finding(
                CHECKER, path, node.lineno,
                f"metric '{name}' missing from README's metric list"))
    return findings
