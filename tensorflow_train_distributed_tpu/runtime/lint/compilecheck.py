"""Compile-discipline pass: jit-boundary shape analyzer + recompile sanitizer.

The stack's TPU performance story assumes every hot ``jax.jit`` site
compiles once per shape bucket and never again — one un-bucketed
prompt length or python-scalar closure reaching a jit boundary turns a
~7 ms decode step into a multi-second recompile storm (the dense-MoE
varied-length storm was hand-found in PR 3; this module makes the
whole bug class mechanical).  Same two-half shape as the concurrency
discipline (``concurrency.py`` + ``lockcheck.py``):

- **static checker** (``compilecheck``, registered in ``core``): every
  ``jax.jit`` in the package must be either decorated with
  ``@compile_site(...)`` (``runtime.lint.registry``) or routed through
  the call-style seam ``compilecheck.jit(fn, site=..., ...)`` — the
  declared ``donates``/``statics``/``static_names`` must match the jit
  decorator's ``donate_argnums``/``static_argnums``/``static_argnames``
  exactly (a donation miss silently doubles peak HBM: the cache buffer
  AND its successor both live).  Call sites of annotated programs must
  not feed raw host-measured sizes (``len(...)`` / ``.shape``) across
  the boundary un-bucketed (wrap them in a bucket helper —
  ``_bucket_len`` / ``_pieces_for`` / anything named ``*bucket*``), and
  a jitted closure must not capture a local produced by
  ``len``/``int``/``float``/``.shape`` (the value burns in at trace
  time: every new value is a silent recompile).

- **runtime sanitizer** (``TTD_COMPILECHECK=1``; ``TTD_NO_COMPILECHECK=1``
  is the live escape hatch, re-read per dispatch through the
  ``os.environ._data`` fast path): annotated sites record a
  ``(static args) -> {abstract dynamic signatures}`` map per call
  site.  A dispatch whose signature was seen before is a dict+set
  lookup (two pinned bars, tests/test_compilecheck.py: < 5 us for
  flat-array signatures; < 40 us for pytree-carrying programs, whose
  per-dispatch ``tree_flatten`` is leaf-proportional — ~18 us on the
  llama_tiny decode program, ≈0.04% of a decode chunk); a NEW
  signature is a compile — it increments the process-wide counter
  (``ttd_engine_compiles_total`` on ``/metrics`` samples it) and wraps
  the dispatch in a ``compile/<site>`` flight-recorder span (visible in
  ``/debug/trace`` and ``tools/trace_report.py``), so compile time is
  attributed in the same timeline as everything else.  When the number
  of distinct signatures for one static group exceeds the site's
  declared ``max_compiles`` budget, the first excess dispatch raises
  ``RecompileError`` with the old and new signatures diffed — a
  recompile storm fails the test that exhibits it instead of shipping.
  conftest arms it for all of tier-1, so every serving/training test
  doubles as a recompile-storm test.

Static groups key on the static arguments (the engine/trainer instance
behind ``static_argnums=(0,)``, the config behind ``static_argnames``):
a new engine legitimately compiles its own bucket set, so budgets are
per-instance, not process-global.  ``max_compiles=None`` declares a
deliberately exact-shape site (offline batch APIs like
``models.generate``: one compile per prompt shape is the documented
contract) — recorded and counted, never budget-enforced.
"""

from __future__ import annotations

import ast
import contextlib
import functools
import inspect
import itertools
import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

# events is import-light (stdlib + the registry); it hosts the shared
# fast-env-flag reader and the span recorder the compile spans land in.
from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.lint.core import (
    Finding,
    register_checker,
)
from tensorflow_train_distributed_tpu.runtime.lint.dispatch import (
    _decorator_name,
    _dotted,
    _is_jit_decorated,
)

CHECKER = "compilecheck"

_ARM_ENV = "TTD_COMPILECHECK"
_KILL_ENV = "TTD_NO_COMPILECHECK"


class RecompileError(RuntimeError):
    """A jit site exceeded its declared compile budget (recompile storm)."""


# -- arming ----------------------------------------------------------------


def _truthy(v: Optional[str]) -> bool:
    return v is not None and v not in ("", "0")


def armed() -> bool:
    """``TTD_COMPILECHECK`` truthy and not vetoed by
    ``TTD_NO_COMPILECHECK`` — checked at decoration time (sites wrap at
    import, the lockcheck contract: arm BEFORE importing the package)."""
    if _truthy(os.environ.get(_KILL_ENV)):
        return False
    return _truthy(os.environ.get(_ARM_ENV))


# The veto is ALSO re-read per dispatch (an operator shell can disarm a
# misbehaving sanitizer live, no redeploy) — through the flight
# recorder's shared ``os.environ._data`` fast-path reader (~0.14 us vs
# ~1 us for os.environ.get on a per-chunk path; one implementation of
# the subtle layout probe, see events.make_env_flag_reader).
_vetoed = events.make_env_flag_reader(_KILL_ENV)


# -- site registry + dispatch bookkeeping ----------------------------------


@dataclass(frozen=True)
class SiteSpec:
    """One jit site's declared compile discipline."""

    site: str
    buckets: object = ()           # descriptive: which bucket rule pads
    donates: Tuple[int, ...] = ()
    statics: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    max_compiles: Optional[int] = 8


# Raw lock on purpose: this module is imported by the lint CLI from a
# bare checkout and must never depend on lockcheck's factories being
# (un)installed; the critical sections are leaf-level dict/set updates.
_STATE_LOCK = threading.Lock()
_SITES: Dict[str, SiteSpec] = {}
# (site, static_key) -> {"sigs": set, "last": sig} — the per-instance
# signature groups the budget is enforced over.
_GROUPS: Dict[tuple, dict] = {}
_BUDGET_OVERRIDES: Dict[str, Optional[int]] = {}
_COMPILES = 0
_TOKENS = itertools.count(1)
_TREE_UTIL = None               # lazy jax.tree_util (keep import light)

# -- roofline telemetry ----------------------------------------------------
# Per-site cost ledger the live mfu/mbu gauges read: at each COMPILING
# dispatch the wrapper captures XLA's cost analysis (flops, bytes
# accessed) for the new signature; every dispatch then adds its
# signature's cost to the site's running totals and a bounded
# (t, flops, bytes) window the scrape-time rate is computed over.
# Updates are GIL-atomic dict/deque ops with no lock — a lost increment
# under contention costs a gauge tick, never correctness — and happen
# only when the sanitizer is armed (unarmed, the gauges truthfully
# render no series, the ttd_engine_compiles_total contract).
_COST_WINDOW_S = 10.0
_PROGRAMS: Dict[str, dict] = {}
# site -> {"dispatches": int, "flops": float, "bytes": float,
#          "costs": {sig: (flops, bytes)}, "win": deque[(t, f, b)]}

_PEAK_FLOPS_ENV = "TTD_PEAK_FLOPS"
_PEAK_HBM_ENV = "TTD_PEAK_HBM_BYTES"


def register_site(spec: SiteSpec) -> SiteSpec:
    with _STATE_LOCK:
        _SITES[spec.site] = spec
    return spec


def sites() -> Tuple[str, ...]:
    """Registered site names (populated at import of annotated modules)."""
    with _STATE_LOCK:
        return tuple(sorted(_SITES))


def site_spec(site: str) -> Optional[SiteSpec]:
    with _STATE_LOCK:
        return _SITES.get(site)


def total_compiles() -> int:
    """Process-wide compile events observed at instrumented sites (the
    ``ttd_engine_compiles_total`` source; 0 unless the sanitizer is
    armed)."""
    with _STATE_LOCK:
        return _COMPILES


def reset(site: Optional[str] = None) -> None:
    """Forget recorded signatures (test isolation; the tier-1 suite
    deliberately accumulates).  ``site=None`` clears everything
    including the compile counter."""
    global _COMPILES
    with _STATE_LOCK:
        if site is None:
            _GROUPS.clear()
            _PROGRAMS.clear()
            _COMPILES = 0
        else:
            for key in [k for k in _GROUPS if k[0] == site]:
                del _GROUPS[key]
            _PROGRAMS.pop(site, None)


@contextlib.contextmanager
def override_budget(site: str, max_compiles: Optional[int]):
    """Temporarily replace a site's compile budget (the storm tests'
    lever: plant a 3-signature storm against a budget of 2 instead of
    compiling past a production-sized budget)."""
    missing = object()
    prev = _BUDGET_OVERRIDES.get(site, missing)
    _BUDGET_OVERRIDES[site] = max_compiles
    try:
        yield
    finally:
        if prev is missing:
            _BUDGET_OVERRIDES.pop(site, None)
        else:
            _BUDGET_OVERRIDES[site] = prev


# -- signatures ------------------------------------------------------------


def _skey_contains(skey, entry) -> bool:
    for e in skey:
        if e == entry:
            return True
        if isinstance(e, tuple) and len(e) == 2 and e[1] == entry:
            return True                # (kwarg_name, entry) pairs
    return False


def _purge_token_groups(tok_entry) -> None:
    """Weakref finalizer: a tokened instance (engine/trainer) died —
    drop every signature group keyed on it, so a long-lived armed
    process that churns engines does not leak dead groups (the
    ``_prefix_caches`` lesson, applied to the sanitizer itself)."""
    with _STATE_LOCK:
        for key in [k for k in _GROUPS
                    if _skey_contains(k[1], tok_entry)]:
            del _GROUPS[key]


def _instance_token(x) -> object:
    """A stable per-instance key for static objects (the engine behind
    ``static_argnums=(0,)``).  ``id()`` alone merges a dead engine's
    signature group into whatever object reuses its address — attach a
    monotonic token instead (with a finalizer purging the token's
    groups at gc), falling back to hash (value-keyed configs) then id
    (immutable, unhashable) only when the object refuses it."""
    tok = getattr(x, "__ttd_cc_token__", None)
    if tok is not None:
        return ("tok", tok)
    try:
        tok = next(_TOKENS)
        object.__setattr__(x, "__ttd_cc_token__", tok)
    except (AttributeError, TypeError):
        try:
            return ("hash", type(x).__name__, hash(x))
        except TypeError:
            return ("id", id(x))
    entry = ("tok", tok)
    try:
        weakref.finalize(x, _purge_token_groups, entry)
    except TypeError:
        pass                           # not weakref-able: manual reset()
    return entry


def _static_entry(x) -> object:
    if x is None or type(x) in (bool, int, float, str, bytes):
        return x
    return _instance_token(x)


def _leaf_entry(x) -> object:
    # Shapes are already tuples on jax/np values and dtypes are
    # hashable singletons — keep the raw objects (no tuple copies, no
    # str()): this function is THE per-dispatch cost the <5us bar
    # measures; stringification happens only in error messages.
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (shape, dtype)
    # Python scalars trace weak-typed: abstractly identical per type,
    # value-independent — exactly how jit sees them.
    return ("py", type(x).__name__)


def _dyn_entry(x) -> object:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (shape, dtype)
    if x is None or type(x) in (bool, int, float, complex, str, bytes):
        return ("py", type(x).__name__)
    global _TREE_UTIL
    if _TREE_UTIL is None:
        from jax import tree_util as _TREE_UTIL_mod
        _TREE_UTIL = _TREE_UTIL_mod
    leaves, treedef = _TREE_UTIL.tree_flatten(x)
    try:
        # All-array fast path (the variables/cache trees on every
        # engine dispatch): direct C-property reads, no per-leaf
        # Python call — this loop IS the pytree-site dispatch cost
        # the second overhead bar pins.
        return (treedef, tuple((l.shape, l.dtype) for l in leaves))
    except AttributeError:
        return (treedef, tuple(_leaf_entry(leaf) for leaf in leaves))


def _signature(args, kwargs, static_pos, static_nm):
    """``(static_key, dynamic_signature)`` for one dispatch — the
    static key picks the budget group, the dynamic signature is what a
    new compile looks like."""
    stat: list = []
    dyn: list = []
    for i, a in enumerate(args):
        if i in static_pos:
            stat.append(_static_entry(a))
        else:
            dyn.append(_dyn_entry(a))
    if kwargs:
        for k in sorted(kwargs):
            v = kwargs[k]
            if k in static_nm:
                stat.append((k, _static_entry(v)))
            else:
                dyn.append((k, _dyn_entry(v)))
    return tuple(stat), tuple(dyn)


def _fmt_sig(sig) -> str:
    if sig is None:
        return "<none>"
    return "(" + ", ".join(str(e) for e in sig) + ")"


def _diff_sigs(old, new) -> str:
    if old is None:
        return f"new signature {_fmt_sig(new)}"
    parts = []
    for i in range(max(len(old), len(new))):
        a = old[i] if i < len(old) else "<absent>"
        b = new[i] if i < len(new) else "<absent>"
        if a != b:
            parts.append(f"arg[{i}]: {a} -> {b}")
    return "; ".join(parts) or "identical structure (treedef change)"


def _observe(site: str, spec: SiteSpec, skey, sig) -> Optional[int]:
    """Record one dispatch.  None when the signature was already
    compiled (the fast path); the 1-based signature ordinal when this
    dispatch will compile; raises ``RecompileError`` on the first
    dispatch past the site's budget."""
    key = (site, skey)
    grp = _GROUPS.get(key)
    if grp is not None and sig in grp["sigs"]:
        return None
    global _COMPILES
    with _STATE_LOCK:
        grp = _GROUPS.setdefault(key, {"sigs": set(), "last": None})
        if sig in grp["sigs"]:
            return None
        budget = _BUDGET_OVERRIDES.get(site, spec.max_compiles)
        n = len(grp["sigs"]) + 1
        if budget is not None and n > budget:
            raise RecompileError(
                f"compile budget exceeded at jit site '{site}': this "
                f"dispatch would compile signature #{n} for one static "
                f"group (budget max_compiles={budget}).  "
                f"{_diff_sigs(grp['last'], sig)}.  An un-bucketed "
                f"dynamic dimension is reaching the jit boundary — pad "
                f"it through the site's bucket helpers (declared "
                f"buckets: {spec.buckets!r}), or raise the site's "
                f"max_compiles if the shape set legitimately grew")
        grp["sigs"].add(sig)
        grp["last"] = sig
        _COMPILES += 1
    return n


# -- roofline bookkeeping --------------------------------------------------


def _program(site: str) -> dict:
    p = _PROGRAMS.get(site)
    if p is None:
        p = _PROGRAMS.setdefault(site, {
            "dispatches": 0, "flops": 0.0, "bytes": 0.0,
            "costs": {}, "win": deque(maxlen=8192)})
    return p


def _capture_cost(site: str, sig, fn, args, kwargs) -> None:
    """After a compiling dispatch: ask XLA what the program it just
    built costs (flops, bytes accessed) and remember it per signature.
    ``fn.lower(...).compile()`` hits the executable cache the dispatch
    populated, so the only real work is the trace — per NEW signature,
    never per dispatch.  Anything at all going wrong records a zero
    cost: the roofline is telemetry, a cost model must never take a
    dispatch down."""
    flops = nbytes = 0.0
    try:
        lowered = fn.lower(*args, **kwargs)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0) or 0.0)
        nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    except Exception:  # noqa: BLE001 — stubs/CPU backends may refuse
        pass
    _program(site)["costs"][sig] = (flops, nbytes)


def _count_dispatch(site: str, sig) -> None:
    p = _program(site)
    f, b = p["costs"].get(sig) or (0.0, 0.0)
    p["dispatches"] += 1
    p["flops"] += f
    p["bytes"] += b
    p["win"].append((time.monotonic(), f, b))


def program_stats() -> Dict[str, dict]:
    """Per-site roofline counters: cumulative dispatch/flop/byte
    totals plus flops_per_s / bytes_per_s over the trailing
    ``_COST_WINDOW_S`` window — the numerators the mfu/mbu gauges (and
    a worker's stats relay) consume.  Empty unless the sanitizer is
    armed and an instrumented site has dispatched."""
    now = time.monotonic()
    cutoff = now - _COST_WINDOW_S
    out: Dict[str, dict] = {}
    for site, p in list(_PROGRAMS.items()):
        wf = wb = 0.0
        for t, f, b in list(p["win"]):
            if t >= cutoff:
                wf += f
                wb += b
        out[site] = {
            "dispatches": p["dispatches"],
            "flops_total": p["flops"],
            "bytes_total": p["bytes"],
            "flops_per_s": wf / _COST_WINDOW_S,
            "bytes_per_s": wb / _COST_WINDOW_S,
        }
    return out


def peak_flops_per_s() -> Optional[float]:
    """The mfu denominator: ``TTD_PEAK_FLOPS`` when set (the CPU-test
    and heterogeneous-fleet override), else the device's datasheet peak
    from training.memory — None when unknown (gauges render no series
    rather than a made-up percentage)."""
    raw = os.environ.get(_PEAK_FLOPS_ENV, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            return None
    try:        # lazy: scrape-time only, keeps this module import-light
        import jax
        from tensorflow_train_distributed_tpu.training import memory
        tf = memory.peak_tflops(jax.devices()[0].device_kind)
        return tf * 1e12 if tf else None
    except Exception:  # noqa: BLE001 — no jax / no devices
        return None


def peak_hbm_bytes_per_s() -> Optional[float]:
    """The mbu denominator: ``TTD_PEAK_HBM_BYTES`` (bytes/sec) when
    set, else the device's datasheet HBM bandwidth — None when
    unknown."""
    raw = os.environ.get(_PEAK_HBM_ENV, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            return None
    try:
        import jax
        from tensorflow_train_distributed_tpu.training import memory
        return memory.hbm_bandwidth_bytes_per_sec(
            jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001
        return None


def mfu_by_program() -> Dict[str, float]:
    """``{site: achieved-flops %-of-peak}`` over the trailing window —
    the ``ttd_engine_mfu_pct`` source.  Empty when the peak is unknown
    or nothing dispatched."""
    peak = peak_flops_per_s()
    if not peak:
        return {}
    return {site: round(100.0 * s["flops_per_s"] / peak, 3)
            for site, s in program_stats().items() if s["dispatches"]}


def mbu_by_program() -> Dict[str, float]:
    """``{site: achieved-HBM-bytes %-of-peak}`` over the trailing
    window — the ``ttd_engine_mbu_pct`` source."""
    peak = peak_hbm_bytes_per_s()
    if not peak:
        return {}
    return {site: round(100.0 * s["bytes_per_s"] / peak, 3)
            for site, s in program_stats().items() if s["dispatches"]}


def _wrap(fn, spec: SiteSpec, group=None):
    """The armed wrapper: signature bookkeeping around every dispatch,
    a ``compile/<site>`` span around the compiling ones."""
    site = spec.site
    static_pos = set(spec.statics)
    static_nm = frozenset(spec.static_names)
    # static_argnames callers may still pass positionally (jax accepts
    # both); map names to positions once so the runtime keying matches
    # jit's static/dynamic split either way.
    try:
        params = list(inspect.signature(fn).parameters)
        static_pos |= {params.index(n) for n in spec.static_names
                       if n in params}
    except (ValueError, TypeError):        # pragma: no cover - C callables
        pass
    static_pos = frozenset(static_pos)
    group_tok = None if group is None else _static_entry(group)

    def _observe_call(args, kwargs):
        skey, sig = _signature(args, kwargs, static_pos, static_nm)
        if group_tok is not None:
            skey = (group_tok,) + skey
        return _observe(site, spec, skey, sig), sig

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _vetoed():
            return fn(*args, **kwargs)
        n, sig = _observe_call(args, kwargs)
        if n is None:
            _count_dispatch(site, sig)
            return fn(*args, **kwargs)
        with events.span("compile/" + site, site=site, signature=n):
            out = fn(*args, **kwargs)
        # Roofline: price the program this dispatch just compiled,
        # then count the dispatch at that price.
        if hasattr(fn, "lower"):
            _capture_cost(site, sig, fn, args, kwargs)
        _count_dispatch(site, sig)
        return out

    if hasattr(fn, "lower"):
        def lower(*args, **kwargs):
            """AOT face of the same seam: a ``.lower()`` is a compile
            the sanitizer must see (trainer.lower_train_step routes
            here so the AOT proof and the live step share one site)."""
            if _vetoed():
                return fn.lower(*args, **kwargs)
            n, sig = _observe_call(args, kwargs)
            if n is None:
                return fn.lower(*args, **kwargs)
            with events.span("compile/" + site, site=site, signature=n,
                             aot=True):
                return fn.lower(*args, **kwargs)
        wrapper.lower = lower
    wrapper.__ttd_compile_site__ = site
    wrapper.__ttd_compile_wrapped__ = True
    return wrapper


def _default_site(fn) -> str:
    mod = getattr(fn, "__module__", "") or ""
    qual = getattr(fn, "__qualname__", None) or getattr(
        fn, "__name__", None) or repr(fn)
    return f"{mod.rsplit('.', 1)[-1]}.{qual}"


def annotate(fn, *, buckets=(), donates=(), statics=(), static_names=(),
             max_compiles: Optional[int] = 8, site: Optional[str] = None):
    """Implementation of ``registry.compile_site`` (deferred there to
    keep the registry import-light)."""
    name = site or _default_site(fn)
    spec = register_site(SiteSpec(
        site=name, buckets=buckets, donates=tuple(donates),
        statics=tuple(statics), static_names=tuple(static_names),
        max_compiles=max_compiles))
    try:
        fn.__ttd_compile_site__ = name
    except (AttributeError, TypeError):
        pass                       # C-level jit callables may refuse
    if not armed():
        return fn
    return _wrap(fn, spec)


def jit(fn, *, site: str, buckets=(), max_compiles: Optional[int] = 8,
        group=None, **jit_kwargs):
    """The call-style seam: ``compilecheck.jit(step, site=..., ...)``
    replaces a raw ``jax.jit(step, ...)`` wherever decorator syntax
    cannot reach (the trainer's per-instance step builders and its AOT
    ``.lower()`` path).  ``group`` keys the budget to an owning
    instance (the trainer), since call-style sites have no
    ``static_argnums=(0,)`` self to group by.  Unarmed, this IS
    ``jax.jit`` — same object, zero overhead."""
    import jax

    def _norm(v):
        if v is None:
            return ()
        return tuple(v) if isinstance(v, (tuple, list)) else (v,)

    spec = register_site(SiteSpec(
        site=site, buckets=buckets,
        donates=_norm(jit_kwargs.get("donate_argnums", ())),
        statics=_norm(jit_kwargs.get("static_argnums", ())),
        static_names=_norm(jit_kwargs.get("static_argnames", ())),
        max_compiles=max_compiles))
    jitted = jax.jit(fn, **jit_kwargs)  # ttd-lint: disable=compilecheck -- this IS the instrumented seam every raw jit routes through
    if not armed():
        return jitted
    return _wrap(jitted, spec, group=group)


# -- static checker --------------------------------------------------------

#: Call names sanctioned to carry a host-measured size across a jit
#: boundary: the bucket helpers (anything *bucket*-named) plus the
#: engine's piece-sizing rule.
_BUCKET_HELPERS = {"_pieces_for"}

_SEAM_SUFFIXES = ("compilecheck.jit",)


def _is_seam_call(name: str) -> bool:
    return any(name == s or name.endswith("." + s) for s in _SEAM_SUFFIXES)


def _compile_site_decorator(fn: ast.FunctionDef) -> Optional[ast.expr]:
    for dec in fn.decorator_list:
        name = _decorator_name(dec)
        if name and name.split(".")[-1] == "compile_site":
            return dec
    return None


def _jit_decorator(fn: ast.FunctionDef) -> Optional[ast.expr]:
    for dec in fn.decorator_list:
        name = _decorator_name(dec)
        if name in ("jax.jit", "jit"):
            return dec
        if (isinstance(dec, ast.Call)
                and name in ("partial", "functools.partial")
                and dec.args
                and _dotted(dec.args[0]) in ("jax.jit", "jit")):
            return dec
    return None


def _literal_tuple(node: Optional[ast.expr]) -> Optional[tuple]:
    """Evaluate a literal int/str tuple (or scalar) kwarg; None when
    absent or not a literal (computed specs skip the comparison)."""
    if node is None:
        return ()
    elts = (node.elts if isinstance(node, (ast.Tuple, ast.List))
            else [node])
    out = []
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, (int, str)):
            out.append(e.value)
        else:
            return None
    return tuple(out)


def _kwarg(call: Optional[ast.expr], name: str) -> Optional[ast.expr]:
    if not isinstance(call, ast.Call):
        return None
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _annotation_findings(fn: ast.FunctionDef, path: str) -> List[Finding]:
    """Annotation presence + declared-vs-actual jit kwargs for one
    jit-decorated function."""
    out: List[Finding] = []
    jit_dec = _jit_decorator(fn)
    site_dec = _compile_site_decorator(fn)
    if site_dec is None:
        out.append(Finding(
            CHECKER, path, fn.lineno,
            f"jit site '{fn.name}' is not annotated: declare its "
            f"compile discipline with @compile_site(buckets=..., "
            f"donates=..., statics=...) above the jit decorator (or "
            f"route through compilecheck.jit(site=...))"))
        return out
    pairs = (("donates", "donate_argnums", "donation mismatch doubles "
              "peak HBM: the un-donated buffer and its successor both "
              "live"),
             ("statics", "static_argnums", "the sanitizer keys budget "
              "groups on the declared statics"),
             ("static_names", "static_argnames", "the sanitizer keys "
              "budget groups on the declared statics"))
    for ann_name, jit_name, why in pairs:
        declared = _literal_tuple(_kwarg(site_dec, ann_name))
        actual = _literal_tuple(_kwarg(jit_dec, jit_name))
        if declared is None or actual is None:
            continue               # computed spec: runtime's job
        if tuple(sorted(map(str, declared))) != tuple(
                sorted(map(str, actual))):
            out.append(Finding(
                CHECKER, path, fn.lineno,
                f"'{fn.name}': @compile_site({ann_name}={declared}) "
                f"does not match jax.jit({jit_name}={actual}) — {why}"))
    return out


def _raw_jit_calls(tree: ast.Module, path: str) -> List[Finding]:
    """Standalone ``jax.jit(...)`` calls (not a decorator of an
    annotated function, not the seam) — each must be annotated, routed
    through ``compilecheck.jit``, or suppressed with a reason."""
    decorator_calls = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                decorator_calls.add(id(dec))
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in decorator_calls:
            continue
        if _dotted(node.func) in ("jax.jit", "jit"):
            out.append(Finding(
                CHECKER, path, node.lineno,
                "raw jax.jit(...) call: route it through "
                "compilecheck.jit(fn, site=..., ...) so the "
                "recompilation sanitizer sees the site (or annotate "
                "the decorated form with @compile_site)"))
    return out


def _annotated_callables(tree: ast.Module) -> Set[str]:
    """Names that resolve to compile-site programs in this module:
    decorated functions plus names assigned from the seam."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and _compile_site_decorator(node) is not None:
            names.add(node.name)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _is_seam_call(_dotted(node.value.func) or ""):
            names.add(node.targets[0].id)
    return names


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _scan_unbucketed(node: ast.expr, state: str, flag) -> None:
    """Flag host-measured sizes (``len(...)`` / ``.shape``) that drive
    the jit boundary's SHAPES: bare in the argument expression
    (``state == "top"``, possibly under arithmetic) or inside a
    subscript slice (``state == "slice"`` — ``prompt[:len(prompt)]``,
    THE storm shape).  Wrapping in any non-bucket call (``state ==
    "wrapped"``, e.g. ``jnp.int32(len(prompt))``) turns the value into
    traced DATA — shape-stable, so not flagged; a bucket helper
    (``state == "sanctioned"``) blesses everything under it."""
    if state == "sanctioned":
        return
    if isinstance(node, ast.Call):
        name = _dotted(node.func) or ""
        short = name.split(".")[-1]
        if short == "len" and state in ("top", "slice"):
            flag(node, "len(...)")
        if "bucket" in short or short in _BUCKET_HELPERS:
            inner = "sanctioned"
        elif state == "slice":
            inner = "slice"        # min(len(p), 8) in a slice: still raw
        else:
            inner = "wrapped"
        for child in ast.iter_child_nodes(node):
            _scan_unbucketed(child, inner, flag)
        return
    if isinstance(node, ast.Attribute) and node.attr == "shape" \
            and state in ("top", "slice"):
        flag(node, ".shape")
    if isinstance(node, ast.Subscript):
        _scan_unbucketed(node.value, state, flag)
        _scan_unbucketed(node.slice, "slice", flag)
        return
    for child in ast.iter_child_nodes(node):
        _scan_unbucketed(child, state, flag)


def _unbucketed_findings(tree: ast.Module, path: str) -> List[Finding]:
    annotated = _annotated_callables(tree)
    if not annotated:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_name(node)
        if callee not in annotated:
            continue

        def flag(n, what, _callee=callee):
            out.append(Finding(
                CHECKER, path, n.lineno,
                f"un-bucketed dynamic dim: {what} flows into jit site "
                f"'{_callee}' raw — every distinct value is a silent "
                f"recompile; pad it through a bucket helper "
                f"(_bucket_len / _pieces_for) first"))

        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            _scan_unbucketed(arg, "top", flag)
    return out


_TAINTING = {"len", "int", "float"}


def _tainted_names(fn: ast.FunctionDef) -> Dict[str, str]:
    """Local names assigned from host-measured scalars
    (``len``/``int``/``float`` calls or ``.shape`` reads) — the values
    that freeze into a jitted closure at trace time."""
    tainted: Dict[str, str] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        why = None
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                short = (_dotted(sub.func) or "").split(".")[-1]
                if short in _TAINTING:
                    why = f"{short}(...)"
                    break
            if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                why = ".shape"
                break
        if why:
            tainted[node.targets[0].id] = why
    return tainted


def _closure_leak_findings(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for outer in ast.walk(tree):
        if not isinstance(outer, ast.FunctionDef):
            continue
        tainted = _tainted_names(outer)
        if not tainted:
            continue
        inner_defs = {n.name: n for n in ast.iter_child_nodes(outer)
                      if isinstance(n, ast.FunctionDef)}
        # jit targets: lambdas / inner defs handed to jax.jit or the
        # seam, plus jit-decorated inner defs.
        targets: List[Tuple[ast.AST, int]] = []
        for node in ast.walk(outer):
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                if name in ("jax.jit", "jit") or _is_seam_call(name):
                    if node.args:
                        a0 = node.args[0]
                        if isinstance(a0, ast.Lambda):
                            targets.append((a0, node.lineno))
                        elif isinstance(a0, ast.Name) \
                                and a0.id in inner_defs:
                            targets.append((inner_defs[a0.id],
                                            node.lineno))
        for inner in inner_defs.values():
            if _is_jit_decorated(inner):
                targets.append((inner, inner.lineno))
        seen: Set[Tuple[int, str]] = set()
        for target, lineno in targets:
            args = target.args
            bound = {a.arg for a in
                     args.posonlyargs + args.args + args.kwonlyargs}
            if args.vararg:
                bound.add(args.vararg.arg)
            if args.kwarg:
                bound.add(args.kwarg.arg)
            body = (target.body if isinstance(target.body, list)
                    else [target.body])
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Name) \
                            and isinstance(sub.ctx, ast.Load) \
                            and sub.id in tainted \
                            and sub.id not in bound \
                            and (lineno, sub.id) not in seen:
                        seen.add((lineno, sub.id))
                        out.append(Finding(
                            CHECKER, path, lineno,
                            f"python scalar closure: '{sub.id}' "
                            f"(from {tainted[sub.id]}) is captured by "
                            f"a jitted closure — the value burns in "
                            f"at trace time and every new value "
                            f"recompiles; pass it as a traced "
                            f"argument or bucket it"))
    return out


@register_checker(CHECKER)
def check(tree: ast.Module, lines, path: str, ctx) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and _is_jit_decorated(node):
            findings.extend(_annotation_findings(node, path))
    findings.extend(_raw_jit_calls(tree, path))
    findings.extend(_unbucketed_findings(tree, path))
    findings.extend(_closure_leak_findings(tree, path))
    return findings
