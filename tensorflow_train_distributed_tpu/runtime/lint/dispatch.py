"""Dispatch-purity checker: host-sync and jit hazards.

Two function populations, two hazard sets:

1. ``@dispatch_critical`` functions — the overlap-critical decode
   window (``ServingEngine._dispatch_chunk`` and friends): everything
   between harvesting chunk N and enqueueing chunk N+1 must stay
   sync-free, or the one-chunk lookahead quietly degrades to the
   synchronous path while the A/B still *reports* overlap.  Flagged:

   - ``.block_until_ready()`` — the literal sync;
   - ``np.asarray(...)`` / ``np.array(...)`` / ``jax.device_get`` /
     ``.item()`` / ``float(...)`` / ``int(...)`` on expressions —
     device-value materialization (a host constant is fine; suppress
     with ``# ttd-lint: disable=dispatch`` and say why);
   - ``os.environ[...]`` / ``os.environ.get(...)`` — ~1us per read on
     a per-chunk path; use a module flag read once, or the
     ``os.environ._data`` fast path the flight recorder uses;
   - ``time.time()`` — wall clock (steps under NTP); use
     ``time.monotonic()`` / ``time.perf_counter()``.

2. jitted functions (``@jax.jit`` / ``@partial(jax.jit, ...)`` /
   ``f = jax.jit(g)``) — Python-time effects burn in at TRACE time and
   silently freeze: ``time.*`` clocks, ``random``/``np.random``,
   ``os.environ``, ``print``, plus the same materialization calls
   (a host sync inside a traced fn is a tracer leak).  Also flagged:
   ``jax.jit(..., static_argnums=...)`` call sites in the same module
   whose static argument expression is visibly a traced value
   (a ``jnp.*`` call or a name bound to one) — the classic
   recompile-per-value hazard.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tensorflow_train_distributed_tpu.runtime.lint.core import (
    Finding,
    register_checker,
)

CHECKER = "dispatch"

_CLOCKS = {"time": {"time"}}
_JIT_CLOCKS = {"time": {"time", "monotonic", "perf_counter",
                        "process_time"}}
_MATERIALIZERS = {("np", "asarray"), ("np", "array"),
                  ("numpy", "asarray"), ("numpy", "array"),
                  ("jax", "device_get")}


def _dotted(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _decorator_name(dec: ast.expr) -> Optional[str]:
    target = dec.func if isinstance(dec, ast.Call) else dec
    return _dotted(target)


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = _decorator_name(dec)
        if name in ("jax.jit", "jit"):
            return True
        # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
        if (isinstance(dec, ast.Call)
                and name in ("partial", "functools.partial")
                and dec.args
                and _dotted(dec.args[0]) in ("jax.jit", "jit")):
            return True
    return False


def _is_dispatch_critical(fn: ast.FunctionDef) -> bool:
    return any(_decorator_name(d) == "dispatch_critical"
               for d in fn.decorator_list)


def _hazards(fn: ast.FunctionDef, path: str, jit: bool) -> List[Finding]:
    where = "jitted function" if jit else "dispatch-critical window"
    clocks = _JIT_CLOCKS if jit else _CLOCKS
    out: List[Finding] = []

    def flag(node, msg):
        out.append(Finding(CHECKER, path, node.lineno,
                           f"{fn.name}: {msg} inside {where}"))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            name = _dotted(f) or ""
            if isinstance(f, ast.Attribute):
                if f.attr == "block_until_ready":
                    flag(node, "block_until_ready() host sync")
                    continue
                if f.attr == "item" and not node.args:
                    flag(node, ".item() device-value materialization")
                    continue
                if jit and name.startswith(("random.", "np.random.",
                                            "numpy.random.")):
                    flag(node, f"{name}(): Python-time randomness "
                               f"(burns in at trace time)")
                    continue
                parts = name.split(".")
                if len(parts) == 2:
                    mod, attr = parts
                    if (mod, attr) in _MATERIALIZERS:
                        flag(node, f"{name}() device-value "
                                   f"materialization / host sync")
                        continue
                    if mod in clocks and attr in clocks[mod]:
                        what = ("Python-time clock (burns in at trace "
                                "time)" if jit else
                                "wall clock (use time.monotonic)")
                        flag(node, f"{name}(): {what}")
                        continue
                    if mod == "os" and attr == "urandom" and jit:
                        flag(node, "os.urandom(): Python-time "
                                   "randomness")
                        continue
                if name in ("os.environ.get",):
                    flag(node, "os.environ.get(): slow env read on a "
                               "hot path (hoist to a module flag or "
                               "use the os.environ._data fast path)")
                    continue
            elif isinstance(f, ast.Name):
                if jit and f.id == "print":
                    flag(node, "print(): host side effect at trace "
                               "time")
                    continue
                if f.id in ("float", "int") and len(node.args) == 1:
                    a = node.args[0]
                    if isinstance(a, ast.UnaryOp):
                        a = a.operand        # float(-1e9) is constant
                    if not isinstance(a, ast.Constant):
                        flag(node, f"{f.id}() on a non-constant "
                                   f"(device-value materialization if "
                                   f"the argument is on device)")
                    continue
        elif isinstance(node, ast.Subscript):
            if _dotted(node.value) == "os.environ":
                flag(node, "os.environ[...]: slow env read on a hot "
                           "path (hoist to a module flag or use the "
                           "os.environ._data fast path)")
    return out


def _static_arg_hazards(tree: ast.Module, path: str) -> List[Finding]:
    """``f = jax.jit(g, static_argnums=(k,))`` whose call sites pass a
    visibly-traced expression in a static position."""
    out: List[Finding] = []
    jnp_names: set = set()          # names bound to jnp.* results
    jitted: Dict[str, List[int]] = {}

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t, v = node.targets[0], node.value
            if isinstance(t, ast.Name) and isinstance(v, ast.Call):
                callee = _dotted(v.func) or ""
                if callee.startswith(("jnp.", "jax.numpy.")):
                    jnp_names.add(t.id)
                if callee in ("jax.jit", "jit"):
                    nums: List[int] = []
                    for kw in v.keywords:
                        if kw.arg == "static_argnums":
                            val = kw.value
                            elts = (val.elts
                                    if isinstance(val, (ast.Tuple,
                                                        ast.List))
                                    else [val])
                            for e in elts:
                                if (isinstance(e, ast.Constant)
                                        and isinstance(e.value, int)):
                                    nums.append(e.value)
                    if nums:
                        jitted[t.id] = nums

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted):
            continue
        for k in jitted[node.func.id]:
            if k >= len(node.args):
                continue
            arg = node.args[k]
            traced = (isinstance(arg, ast.Call)
                      and (_dotted(arg.func) or "").startswith(
                          ("jnp.", "jax.numpy."))) or (
                isinstance(arg, ast.Name) and arg.id in jnp_names)
            if traced:
                out.append(Finding(
                    CHECKER, path, node.lineno,
                    f"traced value passed in static_argnums position "
                    f"{k} of jitted '{node.func.id}' (recompiles per "
                    f"value; pass it traced or hash a host scalar)"))
    return out


@register_checker(CHECKER)
def check(tree: ast.Module, lines, path: str, ctx) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            if _is_dispatch_critical(node):
                findings.extend(_hazards(node, path, jit=False))
            if _is_jit_decorated(node):
                findings.extend(_hazards(node, path, jit=True))
    findings.extend(_static_arg_hazards(tree, path))
    return findings
