"""Kill-switch / env-flag checker.

Every ``TTD_*`` name referenced in package or tools source must be
(1) documented in README.md and (2) exercised by at least one test —
an undocumented kill switch is an operator trap, and an untested one
is a switch nobody knows still works.  This includes stdout tags that
LOOK like env vars (``TTD_RESULT:`` — documented all the same: an
operator grepping logs meets it before reading the source).

Family names are honored: ``TTD_K8S_REPLICAS`` is satisfied by README
documenting either the exact name or a ``TTD_K8S_*`` family entry.
Suppress a deliberate exception with
``# ttd-lint: disable=kill-switch`` on the referencing line.
"""

from __future__ import annotations

import re
from typing import List

from tensorflow_train_distributed_tpu.runtime.lint.core import (
    Finding,
    register_checker,
)

CHECKER = "kill-switch"

# Trailing underscore excluded: ``TTD_FOO_*`` family globs in docs
# are not variable references.
_VAR_RE = re.compile(r"\bTTD_[A-Z0-9_]*[A-Z0-9]\b")


def _family_documented(var: str, doc: str) -> bool:
    """Exact name, or any ``TTD_FOO_*`` family glob whose prefix
    matches the var."""
    if var in doc:
        return True
    parts = var.split("_")
    for i in range(2, len(parts)):
        if "_".join(parts[:i]) + "_*" in doc:
            return True
    return False


@register_checker(CHECKER)
def check(tree, lines, path: str, ctx) -> List[Finding]:
    readme = ctx.read_doc("README.md")
    tests = ctx.tests_corpus()
    findings: List[Finding] = []
    seen: set = set()
    for lineno, line in enumerate(lines, start=1):
        for m in _VAR_RE.finditer(line):
            var = m.group(0)
            if var in seen:
                continue
            seen.add(var)
            if not _family_documented(var, readme):
                findings.append(Finding(
                    CHECKER, path, lineno,
                    f"env flag {var} is not documented in README.md"))
            if var not in tests:
                findings.append(Finding(
                    CHECKER, path, lineno,
                    f"env flag {var} is not exercised by any test "
                    f"under tests/"))
    return findings
