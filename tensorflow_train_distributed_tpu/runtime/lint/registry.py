"""Annotation registry: the vocabulary both lint halves read.

Declarations live IN the code they protect, as plain attributes the
static checker parses from the AST and the runtime sanitizer reads
live:

- ``@thread_role("handler", ...)`` on a function/method declares which
  thread role(s) it runs on.  The static concurrency checker seeds its
  per-class call-graph role propagation from these; when the runtime
  sanitizer is armed (``TTD_LOCKCHECK=1``) the decorator additionally
  tags the calling thread with the role for the duration of the call
  (only when the thread has no role yet — a role marks the THREAD
  ENTRY, nested annotated calls keep the outer identity), which is how
  the per-attribute guards know who is touching them.

- ``@locks_held("_cv")`` declares a helper that must only be called
  with the named lock(s) already held: the checker verifies every call
  site instead of the body's (lock-free) accesses.

- ``@dispatch_critical`` marks a function as living inside the
  overlap-critical decode window: the dispatch-purity checker forbids
  host syncs (``block_until_ready``, ``np.asarray`` on device values,
  ``.item()``, slow ``os.environ`` reads) in it.

- ``_GUARDED_BY`` (class attribute) maps shared-attribute name ->
  guard spec.  A spec is ``("_lock",)`` (every access must hold
  ``self._lock``), ``("_lock", "role", ...)`` (writes must hold the
  lock; lock-free reads are allowed on the listed owner role(s) —
  the single-writer/locked-reader pattern), or ``(None, "role", ...)``
  (no lock: an atomic-publish attribute only the owner role(s) may
  write; anyone may read a single field).  ``@concurrency_guarded``
  on the class validates the spec and, when the sanitizer is armed,
  installs the runtime per-attribute guards.

Known thread roles (``THREAD_ROLES``) are closed on purpose: a typo'd
role must fail loudly, and a NEW role is a design event the registry
should witness.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Callable, Dict, Optional, Tuple

#: The thread roles this codebase runs (see README "Static analysis &
#: concurrency discipline").  main: the process main thread (CLIs,
#: tests, offline serve/bench loops).  handler: gateway HTTP handler
#: threads.  driver: an EngineDriver loop thread (one per replica) —
#: the only role that may touch a ServingEngine's mutating surface.
#: pump: a ReplicaPool per-request pump thread.  watchdog: the replica
#: pool's health-monitor thread.  supervisor: the training supervisor's
#: relaunch loop.  loadgen: bench load-generation threads.  trainer:
#: the training host loop (fit + host callbacks).  reader: a frame
#: reader over a subprocess replica's driver protocol (one per worker,
#: both sides: the parent-side ProcDriver reader and the worker's
#: frame loop) — the only role that may touch a ProcDriver's
#: parent-side request table.  scaler: the elastic proc pool's
#: scale/respawn thread (spawns and drains workers; owns the published
#: replica list).
#: acceptor: the network pool's TCP listener thread (admits dial-in
#: workers; owns NetPool's published replica list the way the scaler
#: owns ProcPool's).  dialer: a standalone worker daemon's
#: gateway-dialing loop (tools/serve_worker — connect, serve, re-dial
#: with backoff).
THREAD_ROLES = frozenset({
    "main", "handler", "driver", "pump", "watchdog", "supervisor",
    "loadgen", "trainer", "reader", "scaler", "acceptor", "dialer",
})

_ROLE_TLS = threading.local()


def _sanitizer_armed() -> bool:
    # Import-cycle-free read (lockcheck imports nothing from here at
    # module scope); decoration-time check, deliberately cheap.
    if os.environ.get("TTD_NO_LOCKCHECK", "0") not in ("", "0"):
        return False
    return os.environ.get("TTD_LOCKCHECK", "0") not in ("", "0")


def current_role() -> Optional[str]:
    """The role tag of the calling thread (None when untagged — e.g.
    a test poking internals directly; the runtime guards let untagged
    threads through and leave enforcement to the static checker)."""
    stack = getattr(_ROLE_TLS, "stack", None)
    return stack[-1] if stack else None


def _push_role(role: str) -> bool:
    stack = getattr(_ROLE_TLS, "stack", None)
    if stack is None:
        stack = _ROLE_TLS.stack = []
    if stack:
        return False          # thread entry already tagged: keep it
    stack.append(role)
    return True


def _pop_role() -> None:
    _ROLE_TLS.stack.pop()


def thread_role(*roles: str) -> Callable:
    """Declare the thread role(s) a function runs on.

    Multiple roles mean "any of these" (e.g. an engine scrape accessor
    serving both the driver loop and handler-thread scrapes).  The
    FIRST role is the one the runtime sanitizer tags the thread with
    when the function is a thread entry point.
    """
    if not roles:
        raise ValueError("thread_role needs at least one role")
    for r in roles:
        if r not in THREAD_ROLES:
            raise ValueError(
                f"unknown thread role {r!r} (known: "
                f"{sorted(THREAD_ROLES)}); new roles are added in "
                f"runtime/lint/registry.py, deliberately")

    def deco(fn):
        fn.__ttd_thread_roles__ = tuple(roles)
        if not _sanitizer_armed():
            return fn

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _push_role(roles[0]):
                try:
                    return fn(*args, **kwargs)
                finally:
                    _pop_role()
            return fn(*args, **kwargs)

        wrapper.__ttd_thread_roles__ = tuple(roles)
        return wrapper

    return deco


def locks_held(*locks: str) -> Callable:
    """Declare a helper callable only with the named lock(s) held
    (checked at every call site by the static concurrency checker;
    the body is then analyzed as if the locks were held)."""
    if not locks:
        raise ValueError("locks_held needs at least one lock name")

    def deco(fn):
        fn.__ttd_locks_held__ = tuple(locks)
        return fn

    return deco


def dispatch_critical(fn: Callable) -> Callable:
    """Mark a function as inside the overlap-critical decode window
    (no host syncs allowed — the dispatch-purity checker enforces)."""
    fn.__ttd_dispatch_critical__ = True
    return fn


def compile_site(*, buckets=(), donates=(), statics=(), static_names=(),
                 max_compiles: Optional[int] = 8,
                 site: Optional[str] = None) -> Callable:
    """Declare a hot ``jax.jit`` site's compile discipline (stack this
    ABOVE the jit decorator).

    - ``buckets``: which bucket rule pads this site's dynamic dims
      (descriptive — ``"prompt_buckets"``, ``"exact"``; it lands in the
      RecompileError so the storm message names the missing padding);
    - ``donates`` / ``statics`` / ``static_names``: must mirror the jit
      decorator's ``donate_argnums`` / ``static_argnums`` /
      ``static_argnames`` exactly — the static ``compilecheck`` checker
      cross-checks them (a donation miss doubles peak HBM; the statics
      key the sanitizer's budget groups);
    - ``max_compiles``: distinct compiled signatures allowed per static
      group (per engine/trainer instance) before the runtime sanitizer
      (``TTD_COMPILECHECK=1``) raises ``RecompileError``.  ``None``
      declares a deliberately exact-shape batch API: recorded, counted
      on ``ttd_engine_compiles_total``, never budget-enforced.

    Like ``@thread_role``, the declaration is free when the sanitizer
    is unarmed: the function comes back untouched.
    """
    def deco(fn):
        # Deferred import: the registry stays import-light (the
        # lockcheck/concurrency_guarded convention).
        from tensorflow_train_distributed_tpu.runtime.lint import (
            compilecheck,
        )

        return compilecheck.annotate(
            fn, buckets=buckets, donates=donates, statics=statics,
            static_names=static_names, max_compiles=max_compiles,
            site=site)

    return deco


def memory_budget(*, pool, budget_bytes: Optional[int] = None,
                  budget_fn: Optional[Callable] = None,
                  project_fn: Optional[Callable] = None,
                  lifetime="owner",
                  site: Optional[str] = None) -> Callable:
    """Declare a device-memory ALLOCATOR's pool and budget (the third
    lint vertical — memory — mirroring ``@compile_site`` for compiles).

    - ``pool``: the pool name the allocation charges (str, or a
      callable over the allocator's args for multi-pool allocators —
      the engine's ``_fresh_cache`` mints grid pools AND batch-1
      prefill caches);
    - ``budget_bytes`` / ``budget_fn``: the owner's HBM budget in
      bytes (a callable receives the allocator's args; returning None
      means track-only — gauges and spans, no enforcement).  One of
      the two is REQUIRED (the static checker flags a budget-less
      declaration);
    - ``project_fn``: projected bytes of the allocation BEFORE it runs
      (the engine's memoized cache ``eval_shape``) — with it, an
      over-budget allocation raises ``MemoryBudgetError`` before any
      buffer exists; without it, the first call of each signature
      charges after the fact and later calls pre-check off the memo;
    - ``lifetime``: ``"owner"`` (charge lives until the owning
      instance dies — the constant pools) or ``"leaf"`` (released as
      the minted buffers die — transient allocations); callable for
      allocators that mint both.

    Like ``@thread_role`` and ``@compile_site``, the declaration is
    free when the sanitizer (``TTD_MEMCHECK=1``) is unarmed: the
    function comes back untouched.
    """
    def deco(fn):
        # Deferred import: the registry stays import-light (the
        # compile_site convention).
        from tensorflow_train_distributed_tpu.runtime.lint import (
            memcheck,
        )

        return memcheck.annotate(
            fn, pool=pool, budget_bytes=budget_bytes,
            budget_fn=budget_fn, project_fn=project_fn,
            lifetime=lifetime, site=site)

    return deco


def _normalize_spec(attr: str, spec) -> Tuple[Optional[str], Tuple[str, ...]]:
    """-> (lock_name_or_None, owner_roles)."""
    if isinstance(spec, str):
        return spec, ()
    if isinstance(spec, (tuple, list)) and spec:
        lock = spec[0]
        owners = tuple(spec[1:])
        if lock is not None and not isinstance(lock, str):
            raise TypeError(f"_GUARDED_BY[{attr!r}]: lock must be a str "
                            f"or None, got {lock!r}")
        for r in owners:
            if r not in THREAD_ROLES:
                raise ValueError(
                    f"_GUARDED_BY[{attr!r}]: unknown owner role {r!r}")
        if lock is None and not owners:
            raise ValueError(
                f"_GUARDED_BY[{attr!r}]: a lockless attribute needs at "
                f"least one owner role")
        return lock, owners
    raise TypeError(f"_GUARDED_BY[{attr!r}]: spec must be a str or a "
                    f"non-empty tuple, got {spec!r}")


def guard_specs(cls) -> Dict[str, Tuple[Optional[str], Tuple[str, ...]]]:
    """The class's normalized ``_GUARDED_BY`` declarations."""
    raw = getattr(cls, "_GUARDED_BY", None) or {}
    return {attr: _normalize_spec(attr, spec) for attr, spec in raw.items()}


def concurrency_guarded(cls):
    """Class decorator: validate ``_GUARDED_BY`` and (when the runtime
    sanitizer is armed) install per-attribute access guards."""
    specs = guard_specs(cls)        # raises on malformed declarations
    if specs and _sanitizer_armed():
        # Deferred import: lockcheck pulls nothing heavy, but keeping
        # the registry import-light matters for child processes.
        from tensorflow_train_distributed_tpu.runtime.lint import lockcheck
        lockcheck.install_attr_guards(cls, specs)
    return cls
