"""Runtime lock-order sanitizer (``TTD_LOCKCHECK=1``).

The dynamic half of the concurrency discipline: while the static
checker proves lock *presence* on code paths, this module watches the
locks actually *move* and raises the moment an execution exhibits a
hazard — so every existing gateway/replica/chaos test doubles as a
race test when conftest arms it for tier-1:

- **acquisition-order graph**: every instrumented lock acquisition
  while other instrumented locks are held records ``held -> acquired``
  edges keyed by the locks' CREATION SITES (all ``EngineDriver._cv``
  instances share one node — the ordering class is the invariant, not
  the instance).  A new edge that closes a cycle raises
  ``LockOrderError`` with both directions' first-seen sites: the
  classic ABBA deadlock, caught on the first run that exhibits both
  orders, no hang required.  Nested acquisition of two SIBLING locks
  from the same creation site raises too (there is no consistent
  order between anonymous siblings).
- **guarded-attribute access**: classes decorated
  ``@concurrency_guarded`` get per-attribute descriptors enforcing
  their ``_GUARDED_BY`` spec live — an access from a role-tagged
  thread that neither holds the declared lock nor owns the attribute
  raises ``GuardViolation`` at the exact access.  Untagged threads
  (tests poking internals) pass through: runtime enforcement targets
  the package's own thread roles; the static checker covers the rest.

Instrumentation is scoped to locks CREATED BY PACKAGE CODE: the
installed factories inspect the creating frame and hand everything
else (jax, stdlib queue/logging, test code) the raw primitive —
overhead lands only where the invariants live.  ``install()`` is
idempotent; ``TTD_NO_LOCKCHECK=1`` vetoes arming entirely (the escape
hatch when the sanitizer itself misbehaves in the field).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

from tensorflow_train_distributed_tpu.runtime.lint import registry

_PKG_PREFIX = "tensorflow_train_distributed_tpu"

# Raw primitives captured before any patching (the sanitizer's own
# bookkeeping must never recurse into itself).
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock
_RAW_CONDITION = threading.Condition


class LockOrderError(RuntimeError):
    """Two locks were acquired in both orders (potential deadlock)."""


class GuardViolation(RuntimeError):
    """A guarded attribute was touched without its declared lock."""


class _Held(threading.local):
    def __init__(self):
        self.stack: List["_InstrumentedLock"] = []


_HELD = _Held()
_GRAPH_GUARD = _RAW_LOCK()
# src name -> dst name -> first-seen description.
_EDGES: Dict[str, Dict[str, str]] = {}


def reset_graph() -> None:
    """Forget recorded edges (test isolation for the sanitizer's own
    tests; the tier-1 suite deliberately accumulates)."""
    with _GRAPH_GUARD:
        _EDGES.clear()


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst over recorded edges (caller holds guard)."""
    stack = [(src, [src])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in _EDGES.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


class _InstrumentedLock:
    """Order-recording wrapper over a raw Lock/RLock.

    Speaks the full lock protocol plus the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` trio ``threading.Condition``
    uses, so a Condition built over one keeps exact wait semantics
    while the sanitizer keeps exact held-state."""

    __slots__ = ("_inner", "name", "_reentrant", "_owner", "_count")

    def __init__(self, inner, name: str, reentrant: bool):
        self._inner = inner
        self.name = name
        self._reentrant = reentrant
        self._owner: Optional[int] = None
        self._count = 0

    # -- sanitizer bookkeeping -------------------------------------------

    def held_by_current(self) -> bool:
        return self._owner == threading.get_ident()

    def _record_acquired(self) -> None:
        held = _HELD.stack
        me = threading.get_ident()
        if held:
            with _GRAPH_GUARD:
                for h in held:
                    if h is self:
                        continue
                    if h.name == self.name:
                        raise LockOrderError(
                            f"nested acquisition of two sibling locks "
                            f"from the same creation site {self.name} "
                            f"(no consistent order can exist between "
                            f"anonymous siblings)")
                    back = _find_path(self.name, h.name)
                    if back is not None:
                        raise LockOrderError(
                            f"lock-order cycle: acquiring {self.name} "
                            f"while holding {h.name}, but the reverse "
                            f"order {' -> '.join(back)} was already "
                            f"recorded ({_EDGES[back[0]][back[1]]}) — "
                            f"potential ABBA deadlock")
                    _EDGES.setdefault(h.name, {}).setdefault(
                        self.name,
                        f"first seen on thread {me}")
        held.append(self)
        self._owner = me
        self._count = 1

    def _record_released(self) -> None:
        self._owner = None
        self._count = 0
        stack = _HELD.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    # -- lock protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._inner.acquire(blocking, timeout)
            self._count += 1
            return True
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._record_acquired()
            except BaseException:
                # The order violation is the error to surface — but the
                # raw lock must not stay held behind it.
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        if self._reentrant and self._owner == threading.get_ident() \
                and self._count > 1:
            self._count -= 1
            self._inner.release()
            return
        self._record_released()
        self._inner.release()

    def locked(self) -> bool:
        if self._reentrant:
            # _thread.RLock has no .locked() before 3.14; ownership
            # tracking answers the same question.
            return self._owner is not None
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # -- Condition protocol ----------------------------------------------

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        # Bookkeeping BEFORE the raw release, mirroring release(): the
        # moment the raw lock drops, another thread may acquire and
        # set _owner/_count — recording after would clobber the new
        # holder's state (spurious GuardViolations on legitimately
        # locked accesses) and could capture ITS count as ours.
        saved = self._count
        self._record_released()
        inner_state = (self._inner._release_save()
                       if hasattr(self._inner, "_release_save")
                       else self._inner.release())
        return (inner_state, saved)

    def _acquire_restore(self, state) -> None:
        inner_state, saved = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        self._record_acquired()
        self._count = saved

    def __repr__(self) -> str:
        return (f"<InstrumentedLock {self.name} "
                f"owner={self._owner} count={self._count}>")


def make_lock(name: str) -> _InstrumentedLock:
    """An instrumented non-reentrant lock (tests, explicit call sites)."""
    return _InstrumentedLock(_RAW_LOCK(), name, reentrant=False)


def make_rlock(name: str) -> _InstrumentedLock:
    return _InstrumentedLock(_RAW_RLOCK(), name, reentrant=True)


# -- factory installation --------------------------------------------------

_INSTALLED = False


def _creation_site(depth: int = 2) -> Tuple[bool, str]:
    """(created by package code?, "file.py:line") for the frame that
    called the patched factory."""
    try:
        frame = sys._getframe(depth)
    except ValueError:                          # pragma: no cover
        return False, "?"
    mod = frame.f_globals.get("__name__", "")
    site = (f"{os.path.basename(frame.f_code.co_filename)}"
            f":{frame.f_lineno}")
    return mod.startswith(_PKG_PREFIX), site


def _lock_factory():
    ours, site = _creation_site()
    if ours:
        return _InstrumentedLock(_RAW_LOCK(), site, reentrant=False)
    return _RAW_LOCK()


def _rlock_factory():
    ours, site = _creation_site()
    if ours:
        return _InstrumentedLock(_RAW_RLOCK(), site, reentrant=True)
    return _RAW_RLOCK()


def _condition_factory(lock=None):
    ours, site = _creation_site()
    if ours and lock is None:
        # The Condition's hidden RLock is where the driver's ordering
        # lives: instrument it so ``with self._cv`` edges record.
        lock = _InstrumentedLock(_RAW_RLOCK(), site, reentrant=True)
    return _RAW_CONDITION(lock)


def armed() -> bool:
    """``TTD_LOCKCHECK`` truthy and not vetoed by ``TTD_NO_LOCKCHECK``
    — ONE truthiness rule for both sanitizer halves (role tagging /
    guard install in the registry, lock-factory patching here)."""
    return registry._sanitizer_armed()


def installed() -> bool:
    return _INSTALLED


def install() -> bool:
    """Patch the lock factories (idempotent).  Call BEFORE importing
    the package modules whose objects should be instrumented — lock
    instances are wrapped at CREATION, so anything constructed earlier
    stays raw (and is simply not checked).  Returns True when armed
    and installed."""
    global _INSTALLED
    if not armed():
        return False
    if _INSTALLED:
        return True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _INSTALLED = True
    return True


def uninstall() -> None:
    global _INSTALLED
    threading.Lock = _RAW_LOCK
    threading.RLock = _RAW_RLOCK
    threading.Condition = _RAW_CONDITION
    _INSTALLED = False


# -- guarded-attribute runtime enforcement ---------------------------------


class _AttrGuard:
    """Data descriptor enforcing one ``_GUARDED_BY`` entry live."""

    __slots__ = ("attr", "lock_name", "owners", "_key")

    def __init__(self, attr: str, lock_name: Optional[str],
                 owners: Tuple[str, ...]):
        self.attr = attr
        self.lock_name = lock_name
        self.owners = owners
        self._key = f"__ttd_guarded_{attr}"

    def _check(self, inst, writing: bool) -> None:
        role = registry.current_role()
        if role is None:
            return          # untagged thread: static checker territory
        if self.lock_name is None:
            # Atomic-publish attribute: owner-only writes, free reads.
            if writing and role not in self.owners:
                raise GuardViolation(
                    f"{type(inst).__name__}.{self.attr}: write from "
                    f"role '{role}' (owners: {self.owners})")
            return
        lock = getattr(inst, self.lock_name, None)
        if isinstance(lock, _RAW_CONDITION):
            # A Condition-guarded attribute (EngineDriver's ``_cv``):
            # the ordering/ownership state lives in the Condition's
            # INNER lock, which the factory instrumented at creation.
            lock = getattr(lock, "_lock", None)
        if not isinstance(lock, _InstrumentedLock):
            return          # raw/absent lock: cannot verify, let it go
        if lock.held_by_current():
            return
        if role in self.owners:
            # Owner-role lock-free access: reads are the sanctioned
            # single-writer pattern; container writes are statically
            # checked (a descriptor cannot see them anyway).
            return
        raise GuardViolation(
            f"{type(inst).__name__}.{self.attr}: access from role "
            f"'{role}' without holding self.{self.lock_name} "
            f"(owners: {self.owners or '()'})")

    def __get__(self, inst, owner=None):
        if inst is None:
            return self
        try:
            value = inst.__dict__[self._key]
        except KeyError:
            raise AttributeError(self.attr) from None
        self._check(inst, writing=False)
        return value

    def __set__(self, inst, value) -> None:
        if self._key in inst.__dict__:      # first write = construction
            self._check(inst, writing=True)
        inst.__dict__[self._key] = value

    def __delete__(self, inst) -> None:
        self._check(inst, writing=True)
        del inst.__dict__[self._key]


def install_attr_guards(cls, specs) -> None:
    """Install runtime guards for a ``@concurrency_guarded`` class
    (called by the registry decorator when the sanitizer is armed)."""
    for attr, (lock_name, owners) in specs.items():
        setattr(cls, attr, _AttrGuard(attr, lock_name, owners))
