"""Preemption-coordinated checkpointing and failure detection.

TPU-native rebuild of the reference's fault-tolerance stack (SURVEY.md
§5.3): ``PreemptionCheckpointHandler``
(``failure_handling/failure_handling.py:337`` — catch SIGTERM/maintenance
events, save a checkpoint, coordinate a synchronized exit at the same step
on every worker), the ``preemption_watcher.py:45`` watcher, and the MWMS
peer health check (``collective_all_reduce_strategy.py:990``).

Mechanics here:

- ``PreemptionWatcher`` — installs a SIGTERM handler (the signal cloud
  schedulers deliver before reclaiming capacity) that flips a flag; no work
  happens in signal context.
- ``sync_preemption_flag`` — the *coordination* step the reference does via
  its gRPC coordination service: all processes agree whether anyone was
  preempted, so every host saves at the same step and exits together
  (divergent save steps would corrupt keep-N GC and deadlock collectives).
  Cross-host agreement rides an all-gather through the live mesh; on one
  process it's the local flag.
- ``PreemptionCheckpointCallback`` — trainer callback: on the first synced
  step after preemption, force-save, block until durable, stop training.
  Resume then picks up from this exact step (``launch.run`` restores
  latest), reproducing the reference's BackupAndRestore-on-SIGTERM flow.

Liveness (the health-check analog): the XLA coordination service that
``jax.distributed.initialize`` connects to already heartbeats every
process and fails collectives on dead peers — the reference's
``_check_health`` thread re-implemented that for NCCL; here it's inherited.
``missed_heartbeat_timeout`` is surfaced in ``runtime.distributed``.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

import jax
import numpy as np

logger = logging.getLogger(__name__)

# The preemption exit-code contract, shared with ``runtime.supervisor``:
# a process exiting with THIS code checkpointed and stopped on purpose
# (SIGTERM'd by convention: 128 + 15).  Supervisors relaunch it WITHOUT
# consuming the crash restart budget — any other nonzero exit is a
# crash.  Keep launch.py, the supervisor, and external schedulers
# agreeing on the one constant.
PREEMPTION_EXIT_CODE = 143


class PreemptionWatcher:
    """Flags termination signals without doing work in signal context.

    ``install()`` chains any pre-existing handler (so test harnesses and
    outer supervisors keep working).  ``preempted`` may also be set
    programmatically (maintenance-event pollers, tests).
    ``watch_sigint=True`` adds SIGINT — Ctrl-C on an interactive run
    then means "checkpoint and stop" instead of a stack-trace death
    (the reference's ``CheckpointManagerV2`` keyboard-interrupt save).
    """

    def __init__(self, signals=(signal.SIGTERM,), *,
                 watch_sigint: bool = False):
        if watch_sigint and signal.SIGINT not in signals:
            signals = tuple(signals) + (signal.SIGINT,)
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def mark_preempted(self) -> None:
        self._event.set()

    def install(self) -> "PreemptionWatcher":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "PreemptionWatcher.install() must run on the main thread "
                "(signal.signal requirement)")
        for sig in self.signals:
            self._prev[sig] = signal.getsignal(sig)
            signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame):
        self._event.set()
        logger.warning("received signal %d: preemption flagged", signum)
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)


def sync_preemption_flag(local_flag: bool) -> bool:
    """True iff ANY process was preempted (all-host agreement).

    The reference reaches this agreement through its coordination service
    (``coordination_service.h``); here the flag is OR-reduced across
    processes so every host takes the checkpoint branch at the same step.
    Single-process: the local flag.
    """
    if jax.process_count() == 1:
        return bool(local_flag)
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([bool(local_flag)]))
    return bool(np.any(flags))


class PreemptionCheckpointCallback:
    """Trainer callback: save-and-stop when any host is preempted.

    Contract (mirrors ``PreemptionCheckpointHandler.run`` semantics): the
    save happens at a step boundary every process reaches, is forced past
    keep-N/interval policies, and is fully durable
    (``wait_until_finished``) before training stops — the checkpoint a
    restarted job resumes from.
    """

    def __init__(self, watcher: PreemptionWatcher,
                 checkpoint_manager=None,
                 *, exit_code: Optional[int] = None, sync_every: int = 10):
        self.watcher = watcher
        self._explicit_manager = checkpoint_manager
        self.exit_code = exit_code
        # Cross-host agreement is a blocking collective; running it every
        # step would tax fast training loops. It runs only on steps where
        # step % sync_every == 0 — a schedule derived from the step counter
        # alone, so every process enters the collective at the same steps
        # (a locally-gated entry would deadlock the all-gather).
        self.sync_every = max(1, sync_every)
        self.saved_step: Optional[int] = None
        self.trainer = None

    def set_trainer(self, trainer):
        self.trainer = trainer

    @property
    def checkpoint_manager(self):
        if self._explicit_manager is not None:
            return self._explicit_manager
        return getattr(self.trainer, "checkpoint_manager", None)

    def on_train_begin(self, state):
        pass

    def on_step_end(self, step: int, metrics) -> Optional[bool]:
        import jax as _jax

        multi = _jax.process_count() > 1
        if multi and step % self.sync_every:
            return None  # off-cadence: no collective, no decision
        flag = (sync_preemption_flag(self.watcher.preempted)
                if multi else self.watcher.preempted)
        if not flag:
            return None
        mgr = self.checkpoint_manager
        state = getattr(self.trainer, "_live_state", None)
        if mgr is not None and state is not None:
            mgr.save(int(state.step), state, force=True)
            mgr.wait_until_finished()
            self.saved_step = int(state.step)
            logger.warning(
                "preemption: checkpoint saved at step %d; stopping",
                self.saved_step)
        else:
            logger.warning("preemption: no checkpoint manager; stopping")
        if self.exit_code is not None:
            raise SystemExit(self.exit_code)
        return True  # request early stop

    def on_epoch_end(self, epoch, metrics):
        return None

    def on_train_end(self, state):
        pass
