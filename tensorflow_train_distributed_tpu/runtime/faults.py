"""Deterministic fault injection for chaos/recovery testing.

The reference validates its fault-tolerance stack by killing workers
under ``MultiProcessRunner`` (SURVEY.md §4.5) — coarse, external, and
only reachable from tests.  This module puts the faults *inside* the
trainer's own seams so recovery machinery (supervisor relaunch,
crash-consistent restore, data-read retry) can be exercised
deterministically from a CLI flag, in CI, against the real code paths.

A **fault plan** is a ``;``-separated list of entries
(``--fault-plan`` / ``TTD_FAULT_PLAN``)::

    step:120:raise              # raise InjectedFault at step 120
    step:200:kill9              # SIGKILL the process at step 200
    step:80:sigterm             # deliver SIGTERM (preemption sim)
    mesh:device_lost:4:step=5   # lose devices at step 5; 4 survive
    ckpt:save:partial           # corrupt the next finished save
    ckpt:save:partial:step=40   # corrupt the step-40 save specifically
    data:read:transient_io:p=0.01   # fail ~1% of record reads (seeded)
    data:read:transient_io:n=2      # fail the first 2 read ATTEMPTS
    serve:dispatch:5:raise          # engine driver dies at dispatch 5
    serve:dispatch:5:hang           # ... hangs mid-dispatch (watchdog)
    serve:dispatch:5:kill9:replica=1    # replica 1 vanishes abruptly
    serve:dispatch:5:killpid:replica=0  # REAL SIGKILL of this process

Mesh-side entries (``mesh:device_lost:<survivors>``) simulate losing
part of the device mesh mid-training: at/after the ``step=`` trigger
(default: the first observed boundary) the trainer raises
``DeviceLost(survivors)`` — the same exception ``launch.py`` converts
real runtime device failures into — which the launcher turns into the
device-loss exit-code contract (``runtime.supervisor``): surviving
device count recorded in the elastic sidecar, exit
``DEVICE_LOSS_EXIT_CODE``, supervisor relaunch onto the survivors with
the checkpoint resharded (``training.checkpoint``).  This is the
trainer-side analog of ``serve:dispatch:kill9`` at mesh granularity.

Serving-side entries (``serve:dispatch``) fire at the engine driver's
Nth decode dispatch — the serving analog of the trainer's step
boundary, so replica failover is chaos-testable the way training
recovery is.  ``replica=K`` scopes an entry to one replica of a
multi-replica gateway; entries without it fire on every driver, each
driver with its own independent ``times`` budget.
Actions mirror the process-level ones at replica granularity:
``raise`` kills the driver loop with error propagation (pending
requests learn immediately), ``hang`` wedges the dispatch
(``hang_s=`` bounds the sleep; default 3600 — the watchdog's prey),
and ``kill9`` makes an IN-PROCESS replica vanish abruptly: the driver
thread exits without resolving a single handle or recording a corpse
— nobody is notified, exactly what SIGKILL looks like to the pool's
liveness monitor.  (A true ``os.kill`` would take every replica in
the process down with it; subprocess replicas get the real thing:)
``killpid`` delivers an ACTUAL ``os.kill(os.getpid(), SIGKILL)`` at
the dispatch boundary — the process is gone before the next
instruction.  It only makes sense inside a subprocess replica worker
(``server.worker`` arms plans from ``TTD_FAULT_PLAN`` in its own
environment, so a ``replica=K``-scoped entry kills exactly one
worker of a pool); armed in a test process or a single-process
gateway it kills THAT process, by design — the whole point is that
nothing survives to fake the signal.

Data-read faults count *attempts*, and the retry loop's attempts count
too: ``n`` below ``filesource.IO_RETRY_ATTEMPTS`` (3) is absorbed by
retry-with-backoff; ``n`` at or above it makes one record's read fail
through its whole budget — the persistent-outage simulation — and the
error propagates.

Every entry accepts ``attempt=K``: it is live only on supervisor
attempt K (``TTD_SUPERVISE_ATTEMPT``, exported by
``runtime.supervisor``) — the knob that makes a kill-at-step-N plan
fire on the first launch and stay quiet after the relaunch, instead of
crash-looping the restart budget away.  Non-probabilistic entries fire
``times`` times (default once) within an attempt.

Injection points are **zero-cost when no plan is armed**: call sites
guard on the module-level ``ARMED`` flag (one attribute read — no
function call, no dict lookup) and only enter this module when a plan
is live.  The armed sites are the trainer step boundary
(``training.trainer``), ``CheckpointManager.save``
(``training.checkpoint``), the record-level reads of the file
sources (``data.filesource`` / ``data.tfrecord``), and the engine
driver's dispatch boundary (``server.driver``).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import threading
import time
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

ENV_PLAN = "TTD_FAULT_PLAN"
ENV_ATTEMPT = "TTD_SUPERVISE_ATTEMPT"

# The one flag injection sites check (module attribute: reading it is a
# single LOAD_ATTR, measured ~40 ns — noise against a >1 ms train step,
# and the read only happens once per host-loop iteration, never inside
# jitted code).
ARMED = False

_PLAN: "Optional[FaultPlan]" = None


class InjectedFault(RuntimeError):
    """A fault raised on purpose by the armed plan (``raise`` action)."""


class InjectedTransientIO(OSError):
    """A transient IO error injected into a record read — the retryable
    kind (``data.filesource.read_with_retries`` absorbs it)."""


class InjectedKill(BaseException):
    """An in-process replica's ``kill9``: the engine driver loop must
    exit WITHOUT resolving handles or recording a failure — SIGKILL
    semantics at thread granularity (a BaseException so ordinary
    ``except Exception`` recovery machinery cannot absorb it)."""


class DeviceLost(RuntimeError):
    """Part of the device mesh failed mid-run.

    ``survivors`` is the usable device count after the loss (None when
    unknown — a real runtime failure where nothing can be probed).
    Raised by the ``mesh:device_lost`` injection point, or converted
    from a real runtime error by ``as_device_loss``; ``launch.py``
    turns it into the device-loss exit-code contract the supervisor
    relaunches on (``runtime.supervisor.DEVICE_LOSS_EXIT_CODE``)."""

    def __init__(self, message: str, survivors: Optional[int] = None):
        super().__init__(message)
        self.survivors = survivors


# Signatures of runtime errors that mean a device (not the program)
# died: the PJRT/XLA strings raised when a chip drops off the ICI
# fabric or its runtime process dies mid-execution.  Deliberately
# narrow — a false positive would reshard a healthy mesh on an
# ordinary crash, silently shrinking the run's compute, and relaunch
# it free of the crash budget.  Generic status-code strings
# ("DATA_LOSS", gRPC's "failed to connect to all addresses") are
# EXCLUDED on purpose: they also decorate corrupted-input reads and
# misconfigured-coordinator failures, which must stay ordinary
# budgeted crashes.
_DEVICE_LOSS_SIGNATURES = (
    "device is in an invalid state",
    "Device or slice has been lost",
    "TPU is in an unhealthy state",
)


def as_device_loss(exc: BaseException) -> Optional[DeviceLost]:
    """``DeviceLost`` view of a runtime error, or None.

    Passes an existing ``DeviceLost`` through; otherwise matches the
    error text against the known device-failure signatures.  Survivor
    count stays None for converted errors — after a real device loss
    the backend cannot be probed from this process; the relaunch
    re-discovers the device set itself."""
    if isinstance(exc, DeviceLost):
        return exc
    text = str(exc)
    if any(sig in text for sig in _DEVICE_LOSS_SIGNATURES):
        return DeviceLost(f"device loss inferred from runtime error: "
                          f"{type(exc).__name__}: {text[:500]}")
    return None


_STEP_ACTIONS = ("raise", "kill9", "sigterm", "exit")
_MESH_ACTIONS = ("device_lost",)
_CKPT_ACTIONS = ("partial",)
_DATA_ACTIONS = ("transient_io",)
_SERVE_ACTIONS = ("raise", "hang", "kill9", "killpid")


@dataclasses.dataclass
class FaultEntry:
    site: str                     # "step" | "ckpt:save" | "data:read"
    action: str
    trigger_step: Optional[int] = None   # step entries: fire at/after it
    params: dict = dataclasses.field(default_factory=dict)
    fired: int = 0
    # serve:dispatch only — fire budget PER DRIVER (keyed by replica
    # id, None standalone): an unscoped entry fires on EVERY replica's
    # driver, `times` times each, instead of N drivers racing one
    # shared budget.
    fired_per: dict = dataclasses.field(default_factory=dict)

    @property
    def times(self) -> int:
        # step/ckpt entries fire `times` times; count-based data entries
        # spell the budget `n` (``data:read:transient_io:n=3``).
        return int(self.params.get("times", self.params.get("n", 1)))

    @property
    def attempt(self) -> Optional[int]:
        a = self.params.get("attempt")
        return None if a is None else int(a)

    def live(self, attempt: int) -> bool:
        if self.attempt is not None and attempt != self.attempt:
            return False
        if self.action == "transient_io" and "p" in self.params:
            return True                  # probabilistic: no fire budget
        return self.fired < self.times


class FaultPlan:
    """Parsed plan + the per-process RNG for probabilistic entries."""

    def __init__(self, entries: list, *, seed: int = 0,
                 attempt: Optional[int] = None):
        self.entries = list(entries)
        self.attempt = (int(os.environ.get(ENV_ATTEMPT, "0"))
                        if attempt is None else int(attempt))
        self._rng = np.random.default_rng(
            np.random.SeedSequence([seed, self.attempt]))
        self._reads = 0

    def __repr__(self) -> str:
        return (f"FaultPlan(attempt={self.attempt}, "
                f"entries={self.entries!r})")


def _parse_params(parts: list) -> dict:
    params = {}
    for p in parts:
        key, sep, val = p.partition("=")
        if not sep or not key:
            raise ValueError(
                f"fault param {p!r} is not key=value")
        try:
            params[key] = float(val) if "." in val else int(val)
        except ValueError:
            raise ValueError(
                f"fault param {p!r}: value must be numeric") from None
    return params


def parse_plan(spec: str, *, seed: int = 0,
               attempt: Optional[int] = None) -> FaultPlan:
    """Parse the plan grammar (module docstring) into a ``FaultPlan``.

    Unknown sites/actions fail here — arming happens at launch time, so
    a typo'd plan dies before any training compute is spent.
    """
    entries = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = [p.strip() for p in raw.split(":")]
        site = parts[0]
        if site == "step":
            if len(parts) < 3:
                raise ValueError(
                    f"fault entry {raw!r}: want step:<N>:<action>")
            try:
                trigger = int(parts[1])
            except ValueError:
                raise ValueError(
                    f"fault entry {raw!r}: step trigger {parts[1]!r} is "
                    "not an integer") from None
            action, rest = parts[2], parts[3:]
            if action == "exit" and rest and "=" not in rest[0]:
                # tolerate step:N:exit:7 for the exit code
                rest = [f"code={rest[0]}"] + rest[1:]
            if action not in _STEP_ACTIONS:
                raise ValueError(
                    f"fault entry {raw!r}: unknown step action "
                    f"{action!r}; have {_STEP_ACTIONS}")
            entries.append(FaultEntry("step", action, trigger,
                                      _parse_params(rest)))
        elif site == "mesh":
            if len(parts) < 3 or parts[1] not in _MESH_ACTIONS:
                raise ValueError(
                    f"fault entry {raw!r}: want "
                    f"mesh:device_lost:<survivors>[:step=N]")
            try:
                survivors = int(parts[2])
            except ValueError:
                raise ValueError(
                    f"fault entry {raw!r}: survivor count {parts[2]!r} is "
                    "not an integer") from None
            if survivors < 1:
                raise ValueError(
                    f"fault entry {raw!r}: survivors must be >= 1 (a "
                    "0-device mesh has nothing to relaunch onto)")
            params = _parse_params(parts[3:])
            params["survivors"] = survivors
            # ``step=`` picks the boundary (default 1: the first one the
            # loop observes) — the step-entry trigger semantics.
            entries.append(FaultEntry(
                "mesh", parts[1], int(params.get("step", 1)), params))
        elif site == "ckpt":
            if len(parts) < 3 or parts[1] != "save":
                raise ValueError(
                    f"fault entry {raw!r}: want ckpt:save:<action>")
            action, rest = parts[2], parts[3:]
            if action not in _CKPT_ACTIONS:
                raise ValueError(
                    f"fault entry {raw!r}: unknown ckpt action "
                    f"{action!r}; have {_CKPT_ACTIONS}")
            entries.append(FaultEntry("ckpt:save", action,
                                      params=_parse_params(rest)))
        elif site == "serve":
            if len(parts) < 4 or parts[1] != "dispatch":
                raise ValueError(
                    f"fault entry {raw!r}: want serve:dispatch:<N>:"
                    f"<action>")
            try:
                trigger = int(parts[2])
            except ValueError:
                raise ValueError(
                    f"fault entry {raw!r}: dispatch trigger {parts[2]!r} "
                    "is not an integer") from None
            action, rest = parts[3], parts[4:]
            if action not in _SERVE_ACTIONS:
                raise ValueError(
                    f"fault entry {raw!r}: unknown serve action "
                    f"{action!r}; have {_SERVE_ACTIONS}")
            entries.append(FaultEntry("serve:dispatch", action, trigger,
                                      _parse_params(rest)))
        elif site == "data":
            if len(parts) < 3 or parts[1] != "read":
                raise ValueError(
                    f"fault entry {raw!r}: want data:read:<action>")
            action, rest = parts[2], parts[3:]
            if action not in _DATA_ACTIONS:
                raise ValueError(
                    f"fault entry {raw!r}: unknown data action "
                    f"{action!r}; have {_DATA_ACTIONS}")
            params = _parse_params(rest)
            if "p" in params and not 0.0 < float(params["p"]) <= 1.0:
                raise ValueError(
                    f"fault entry {raw!r}: p must be in (0, 1]")
            entries.append(FaultEntry("data:read", action, params=params))
        else:
            raise ValueError(
                f"fault entry {raw!r}: unknown site {site!r}; have "
                "step | mesh | ckpt:save | data:read | serve:dispatch")
    if not entries:
        raise ValueError(f"fault plan {spec!r} has no entries")
    return FaultPlan(entries, seed=seed, attempt=attempt)


def arm(plan, *, seed: int = 0) -> FaultPlan:
    """Arm a plan (spec string or ``FaultPlan``) process-wide."""
    global _PLAN, ARMED
    if isinstance(plan, str):
        plan = parse_plan(plan, seed=seed)
    _PLAN = plan
    ARMED = True
    logger.warning("fault plan ARMED: %r", plan)
    return plan


def disarm() -> None:
    global _PLAN, ARMED
    _PLAN = None
    ARMED = False


def arm_from_env(*, seed: int = 0) -> Optional[FaultPlan]:
    """Arm from ``TTD_FAULT_PLAN`` if set (launch calls this once,
    passing the run seed so env- and flag-armed plans produce the same
    probabilistic fault trace)."""
    spec = os.environ.get(ENV_PLAN)
    if not spec:
        return None
    return arm(spec, seed=seed)


def plan() -> Optional[FaultPlan]:
    return _PLAN


def _execute_step_action(entry: FaultEntry, step: int) -> None:
    entry.fired += 1
    if entry.action == "raise":
        raise InjectedFault(f"injected fault at step {step}")
    if entry.action == "kill9":
        logger.warning("fault injection: SIGKILL at step %d", step)
        os.kill(os.getpid(), signal.SIGKILL)
    if entry.action == "sigterm":
        logger.warning("fault injection: SIGTERM at step %d", step)
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if entry.action == "exit":
        code = int(entry.params.get("code", 1))
        logger.warning("fault injection: exit(%d) at step %d", code, step)
        # os._exit: a crash, not an orderly shutdown — no atexit, no
        # checkpoint flush, exactly what a segfault looks like to the
        # supervisor (minus the signal).
        os._exit(code)


def step_boundary(step: int) -> None:
    """Trainer step-boundary injection point.

    Fires entries whose trigger has been reached (``trigger <= step`` —
    with ``steps_per_execution`` k>1 the loop only observes every k-th
    boundary, and a trigger between two boundaries fires at the next
    one rather than never).  ``mesh:device_lost`` entries share the
    boundary: a lost chip surfaces to the host loop at the next
    dispatch, which is exactly here.
    """
    p = _PLAN
    if p is None:
        return
    for entry in p.entries:
        if entry.site not in ("step", "mesh") or not entry.live(p.attempt):
            continue
        if step < entry.trigger_step:
            continue
        if entry.site == "mesh":
            entry.fired += 1
            survivors = int(entry.params["survivors"])
            logger.warning(
                "fault injection: device loss at step %d (%d devices "
                "survive)", step, survivors)
            raise DeviceLost(
                f"injected device loss at step {step} "
                f"({survivors} devices survive)", survivors)
        _execute_step_action(entry, step)


def on_checkpoint_save(step: int, step_dir: str,
                       manager=None) -> None:
    """Checkpoint-save injection point (called AFTER the manager
    reports the save; ``manager`` lets the partial action wait out an
    async save before mutilating the committed dir)."""
    p = _PLAN
    if p is None:
        return
    for entry in p.entries:
        if entry.site != "ckpt:save" or not entry.live(p.attempt):
            continue
        want = entry.params.get("step")
        if want is not None and int(want) != step:
            continue
        entry.fired += 1
        if manager is not None:
            manager.wait_until_finished()
        _make_partial(step_dir)
        logger.warning(
            "fault injection: checkpoint step %d made PARTIAL (%s)",
            step, step_dir)


def _make_partial(step_dir: str) -> None:
    """Turn a committed checkpoint step dir into a crashed-writer one:
    drop the commit marker and truncate the array data so any restore
    attempt fails (not just the marker pre-check)."""
    marker = os.path.join(step_dir, "_CHECKPOINT_METADATA")
    if os.path.exists(marker):
        os.remove(marker)
    for root, _, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            try:
                with open(path, "r+b") as f:
                    f.truncate(max(0, os.path.getsize(path) // 2))
            except OSError:
                pass


# Serve-site firing is the one injection point hit from N concurrent
# driver threads: the budget check-and-bump must be atomic, and the
# ACTION must run outside the lock (a hang holding it would stall every
# other driver's fault check).
_SERVE_LOCK = threading.Lock()


def on_serve_dispatch(n: int, replica: Optional[int] = None) -> None:
    """Engine-driver dispatch injection point (called by
    ``server.driver`` before the Nth ``serve_step``; ``replica`` is the
    driver's replica id in a pool, None standalone).  Triggers fire
    at/after their dispatch ordinal (the step-boundary rule), with an
    independent ``times`` budget PER DRIVER — an entry without
    ``replica=`` fires on every replica; the first matching entry wins
    a given dispatch."""
    p = _PLAN
    if p is None:
        return
    fire = None
    with _SERVE_LOCK:
        for entry in p.entries:
            if entry.site != "serve:dispatch":
                continue
            if entry.attempt is not None and p.attempt != entry.attempt:
                continue
            want = entry.params.get("replica")
            if want is not None and (replica is None
                                     or int(want) != int(replica)):
                continue
            if n < entry.trigger_step:
                continue
            if entry.fired_per.get(replica, 0) >= entry.times:
                continue
            entry.fired_per[replica] = entry.fired_per.get(replica,
                                                           0) + 1
            entry.fired += 1
            fire = entry
            break
    if fire is None:
        return
    if fire.action == "raise":
        raise InjectedFault(
            f"injected serve fault at dispatch {n}"
            + (f" (replica {replica})" if replica is not None else ""))
    if fire.action == "hang":
        hang_s = float(fire.params.get("hang_s", 3600))
        logger.warning(
            "fault injection: hanging dispatch %d (replica %s) "
            "for %gs", n, replica, hang_s)
        time.sleep(hang_s)
        return
    if fire.action == "kill9":
        logger.warning(
            "fault injection: replica %s vanishes at dispatch %d",
            replica, n)
        raise InjectedKill(
            f"injected kill9 at dispatch {n} (replica {replica})")
    if fire.action == "killpid":
        # The REAL thing: SIGKILL this whole process at the dispatch
        # boundary.  No cleanup, no flush, no exception anyone could
        # catch — the subprocess-replica chaos legs arm this in the
        # WORKER's environment so the parent gateway observes a true
        # worker death (EOF on the frame stream, waitpid says signal
        # 9), not a simulation of one.
        logger.warning(
            "fault injection: SIGKILL of pid %d at dispatch %d "
            "(replica %s)", os.getpid(), n, replica)
        os.kill(os.getpid(), signal.SIGKILL)
        return          # pragma: no cover — unreachable past SIGKILL


def on_data_read(index: int) -> None:
    """Record-read injection point (leaf data sources)."""
    p = _PLAN
    if p is None:
        return
    p._reads += 1
    for entry in p.entries:
        if entry.site != "data:read" or not entry.live(p.attempt):
            continue
        if "p" in entry.params:
            if p._rng.random() < float(entry.params["p"]):
                entry.fired += 1
                raise InjectedTransientIO(
                    f"injected transient IO on record {index}")
        else:
            entry.fired += 1
            raise InjectedTransientIO(
                f"injected transient IO on record {index} "
                f"(fault {entry.fired}/{entry.times})")
