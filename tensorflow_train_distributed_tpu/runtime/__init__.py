"""Runtime core: cluster resolution, distributed init, mesh construction.

TPU-native replacement for the reference's cluster-resolver + strategy-factory
layer (``tensorflow/python/distribute/cluster_resolver/*``,
``distribute_lib.py``) — see SURVEY.md §2.2.
"""

from tensorflow_train_distributed_tpu.runtime.distributed import (  # noqa: F401
    DistributedConfig,
    initialize_distributed,
    resolve_cluster,
)
from tensorflow_train_distributed_tpu.runtime.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    force_platform,
    strategy_preset,
)
