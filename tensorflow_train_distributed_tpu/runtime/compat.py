"""jax version-compatibility shims.

The codebase targets current jax (``jax.shard_map`` with ``check_vma=``,
``jax.set_mesh``, ``jax.sharding.get_abstract_mesh``); older jaxlibs in
some containers predate all three.  Import the symbols from here instead
of from ``jax`` so both work: on current jax this module re-exports the
real thing untouched, on old jax it maps onto the era's equivalents —
``jax.experimental.shard_map`` (translating ``check_vma`` to the
pre-rename ``check_rep``), the ``Mesh`` context manager, and the
thread-local physical mesh (whose ``.empty`` / ``.shape`` surface
matches what call sites read).  No other call-signature differences are
papered over — call sites must use keyword arguments (they all do).
"""

import contextlib

import jax

try:
    from jax import shard_map  # noqa: F401  (current jax: re-export)
except ImportError:  # pragma: no cover - exercised only on old jax
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)

try:
    from jax import set_mesh  # noqa: F401  (current jax: re-export)
except ImportError:  # pragma: no cover - exercised only on old jax
    @contextlib.contextmanager
    def set_mesh(mesh):
        # Entering the Mesh sets the thread-local physical mesh that
        # get_abstract_mesh() below reads back — same pairing as
        # current jax's set_mesh/get_abstract_mesh, scoped to `with`.
        with mesh:
            yield mesh

try:
    from jax.lax import axis_size  # noqa: F401  (current jax: re-export)
except ImportError:  # pragma: no cover - exercised only on old jax
    def axis_size(axis_name):
        # psum of a constant is folded to a concrete int at trace time
        # inside shard_map, so this stays usable as a Python loop bound.
        return jax.lax.psum(1, axis_name)


def abstract_mesh(axis_sizes, axis_names):
    """``jax.sharding.AbstractMesh`` across the constructor rename:
    current jax takes ``(sizes_tuple, names_tuple)``, old jax takes one
    ``((name, size), ...)`` tuple."""
    try:
        return jax.sharding.AbstractMesh(tuple(axis_sizes),
                                         tuple(axis_names))
    except TypeError:  # pragma: no cover - exercised only on old jax
        return jax.sharding.AbstractMesh(
            tuple(zip(axis_names, axis_sizes)))


if hasattr(jax.sharding, "get_abstract_mesh"):
    get_abstract_mesh = jax.sharding.get_abstract_mesh
else:  # pragma: no cover - exercised only on old jax
    def get_abstract_mesh():
        from jax._src.mesh import thread_resources

        return thread_resources.env.physical_mesh
