"""Host-wide TPU chip lock: framework processes never overlap on a chip.

Two framework processes touching the single-chip tunnel concurrently
corrupt measurements (observed: a 460% "MFU" timing artifact and a 4×
step-time slowdown under contention — PROFILE.md) and can wedge the
backend.  Every tool that initializes the TPU backend takes this lock
first; CPU-forced runs skip it.

Design (SURVEY §5.8 places serialization host-side, not in XLA):
- ``flock`` on a well-known path — kernel-released on process death, so
  a crashed bench can never deadlock the next one.
- Children spawned by a lock holder inherit the right to run via
  ``TTD_CHIP_LOCK_HELD=1`` in the environment (bench.py runs per-family
  benches as subprocesses for allocator isolation).  Python's subprocess
  closes inherited fds by default, so a spawner that wants the kernel
  lock to survive its own death while a child still drives the chip must
  explicitly pass ``held_fd()`` via ``pass_fds`` — the shared open file
  description then keeps the flock held until the child exits too.
- The holder's pid is written to the file so a waiting process can say
  WHO holds the chip — the "chip held" vs "tunnel dead" diagnosis.
"""

from __future__ import annotations

import contextlib
import errno
import os
import time

LOCK_PATH = os.environ.get("TTD_CHIP_LOCK_PATH", "/tmp/ttd_tpu.lock")
ENV_FLAG = "TTD_CHIP_LOCK_HELD"

_held_fd: int | None = None


def held_fd() -> int | None:
    """Fd of the lock THIS process holds (for subprocess ``pass_fds``)."""
    return _held_fd


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError as e:
        return e.errno == errno.EPERM
    return True


def lock_holder() -> int | None:
    """Pid of the live process holding the chip lock, else None."""
    import fcntl

    try:
        with open(LOCK_PATH, "r+") as f:
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                content = f.read().strip()
                if content.isdigit() and _pid_alive(int(content)):
                    return int(content)
                return None  # held, holder unknown/unreadable
            fcntl.flock(f, fcntl.LOCK_UN)
            return None
    except OSError:
        return None


@contextlib.contextmanager
def chip_lock(timeout: float = 900.0, poll: float = 5.0,
              on_wait=None):
    """Acquire the host-wide chip lock (or inherit it from a parent).

    ``on_wait(holder_pid, waited_s)`` is called once per poll while
    blocked, for progress reporting.  Raises ``TimeoutError`` with the
    holder's pid when the budget runs out — the caller decides whether
    that means "try later" or "steal" (it never means steal here).
    """
    if os.environ.get(ENV_FLAG) == "1":
        yield "inherited"
        return
    import fcntl

    f = open(LOCK_PATH, "a+")
    t0 = time.monotonic()
    try:
        while True:
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                waited = time.monotonic() - t0
                holder = lock_holder()
                if waited >= timeout:
                    raise TimeoutError(
                        f"chip lock {LOCK_PATH} still held"
                        + (f" by pid {holder}" if holder else "")
                        + f" after {waited:.0f}s")
                if on_wait is not None:
                    on_wait(holder, waited)
                time.sleep(poll)
        f.seek(0)
        f.truncate()
        f.write(str(os.getpid()))
        f.flush()
        os.environ[ENV_FLAG] = "1"
        global _held_fd
        _held_fd = f.fileno()
        try:
            yield "acquired"
        finally:
            _held_fd = None
            os.environ.pop(ENV_FLAG, None)
            f.seek(0)
            f.truncate()
            fcntl.flock(f, fcntl.LOCK_UN)
    finally:
        f.close()
