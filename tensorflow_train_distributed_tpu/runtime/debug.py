"""Numerics debug mode: NaN trapping + de-optimized determinism.

SURVEY.md §5.2: the reference has no harness-level race detection — TF core
makes collective ordering deterministic via ordering tokens
(``tensorflow/python/distribute/cross_device_utils.py:274``) and leans on
build-time sanitizers.  XLA serializes collectives by construction, so the
rebuild's observable debug surface is numerics: trap NaNs at the op that
produced them (``jax_debug_nans``) and disable XLA's reordering/fusion
optimizations (``jax_disable_most_optimizations``) so failures localize to
source ops.
"""

from __future__ import annotations

import contextlib
import math

import jax
import numpy as np


@contextlib.contextmanager
def debug_mode(*, nan_checks: bool = True,
               disable_optimizations: bool = False):
    """Context manager toggling JAX debug config, restoring it on exit.

    ``nan_checks`` re-runs any jitted computation that produced a NaN
    op-by-op and raises ``FloatingPointError`` at the culprit; expect a
    large slowdown.  ``disable_optimizations`` additionally turns off most
    XLA optimizations so op boundaries match source.
    """
    updates = {"jax_debug_nans": nan_checks}
    if disable_optimizations:
        updates["jax_disable_most_optimizations"] = True
    # jax.config.values covers flags (jax_disable_most_optimizations) that
    # have no attribute accessor.
    saved = {k: jax.config.values[k] for k in updates}
    try:
        for k, v in updates.items():
            jax.config.update(k, v)
        yield
    finally:
        for k, v in saved.items():
            jax.config.update(k, v)


def assert_tree_finite(tree, name: str = "tree") -> None:
    """Host-side finiteness check over a pytree (params, grads, metrics).

    Raises ``FloatingPointError`` naming every offending leaf path — the
    post-hoc complement to ``debug_mode``'s in-flight trap, cheap enough to
    run at checkpoint boundaries.
    """
    bad = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        arr = np.asarray(jax.device_get(leaf))
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        if not np.isfinite(arr).all():
            n_bad = int((~np.isfinite(arr)).sum())
            bad.append(f"{jax.tree_util.keystr(path)}: {n_bad}/{arr.size} "
                       "non-finite")
    if bad:
        raise FloatingPointError(
            f"{name} has non-finite values:\n  " + "\n  ".join(bad))


def is_finite_scalar(value) -> bool:
    """True for finite floats/ints; False for NaN/inf (metric guard)."""
    try:
        return math.isfinite(float(value))
    except (TypeError, ValueError):
        return True
