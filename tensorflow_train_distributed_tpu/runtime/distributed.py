"""Cluster resolution and multi-host initialization.

TPU-native equivalent of the reference's cluster-resolver stack
(``tensorflow/python/distribute/cluster_resolver/cluster_resolver.py:57``,
``tfconfig_cluster_resolver.py:48``, ``slurm_cluster_resolver.py:164``) and of
the gRPC control plane that ``tf.train.Server`` / ``TF_CONFIG`` set up.  On
TPU, all of that collapses into ``jax.distributed.initialize`` + the XLA
coordination service (the same C++ coordination service TF uses — SURVEY.md
§2.3): one coordinator address, N processes, heartbeats/barriers/KV for free.

Resolution order (first match wins):

1. Explicit arguments / ``DistributedConfig``.
2. ``TTD_COORDINATOR`` / ``TTD_NUM_PROCESSES`` / ``TTD_PROCESS_ID`` env vars
   (this framework's native spelling).
3. ``TF_CONFIG`` JSON env var — accepted for drop-in compatibility with the
   reference harness's launch scripts: ``{"cluster": {"worker": [...]},
   "task": {"type": "worker", "index": k}}`` maps to
   coordinator=worker[0], num_processes=len(workers), process_id=k.
4. Slurm env (``SLURM_PROCID`` / ``SLURM_NTASKS`` / ``SLURM_STEP_NODELIST``).
5. Kubernetes Indexed-Job env (``JOB_COMPLETION_INDEX`` +
   ``TTD_K8S_REPLICAS``; reference ``KubernetesClusterResolver``).
6. GCE metadata via ``TTD_GCE_METADATA`` (inline JSON or ``@file``;
   reference ``GCEClusterResolver``).
7. Single-process (no distributed init needed) — the default on one host.

The K8s/GCE resolvers are deliberately *egress-free*: where the reference
queries the cluster API server / the GCE metadata server at resolve time,
here the same facts arrive through env vars a pod spec or startup script
injects (downward API / one metadata fetch at boot) — resolution itself
never needs the network, so it is testable and works in air-gapped runs.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
from typing import Optional

logger = logging.getLogger(__name__)

_DEFAULT_PORT = 8476


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Where this process sits in the cluster.

    ``num_processes == 1`` means single-process: ``initialize_distributed``
    is a no-op (JAX local mode), matching the reference's default of
    MirroredStrategy on one worker.
    """

    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    source: str = "default"

    @property
    def is_multiprocess(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        """Chief semantics (reference: ``multi_worker_util.is_chief``)."""
        return self.process_id == 0


def _from_env_native() -> Optional[DistributedConfig]:
    coord = os.environ.get("TTD_COORDINATOR")
    nproc = os.environ.get("TTD_NUM_PROCESSES")
    pid = os.environ.get("TTD_PROCESS_ID")
    if not (coord and nproc and pid):
        return None
    return DistributedConfig(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(pid),
        source="env:TTD_*",
    )


def _from_tf_config() -> Optional[DistributedConfig]:
    """Parse the reference harness's ``TF_CONFIG`` cluster spec.

    Mirrors ``TFConfigClusterResolver`` semantics: the ``worker`` job list
    orders processes; ``chief`` (if present) is process 0 and workers follow.
    Parameter-server jobs are rejected — the PS path is re-expressed as
    synchronous SPMD in this framework (SURVEY.md §2.4 "Async PS").
    """
    raw = os.environ.get("TF_CONFIG")
    if not raw:
        return None
    try:
        cfg = json.loads(raw)
        cluster = cfg.get("cluster", {})
        task = cfg.get("task", {})
        if "ps" in cluster:
            raise ValueError(
                "TF_CONFIG declares parameter-server tasks; this framework is "
                "SPMD-only (the reference's ParameterServerStrategy path maps "
                "to a synchronous data/tensor-parallel mesh — launch every "
                "task as a 'worker')."
            )
        chiefs = list(cluster.get("chief", []))
        workers = list(cluster.get("worker", []))
        ordered = chiefs + workers
        if not ordered:
            return None
        ttype = task.get("type", "worker")
        tindex = int(task.get("index", 0))
        if ttype == "chief":
            process_id = tindex
        elif ttype == "worker":
            process_id = len(chiefs) + tindex
        elif ttype == "evaluator":
            # Reference treats the evaluator as outside the training cluster.
            return DistributedConfig(source="tf_config:evaluator")
        else:
            raise ValueError(f"Unsupported TF_CONFIG task type: {ttype!r}")
        return DistributedConfig(
            coordinator_address=ordered[0],
            num_processes=len(ordered),
            process_id=process_id,
            source="env:TF_CONFIG",
        )
    except (json.JSONDecodeError, KeyError) as e:
        raise ValueError(f"Malformed TF_CONFIG: {e}") from e


def _expand_first_slurm_node(nodelist: str) -> str:
    """First hostname from a Slurm nodelist like ``host[3-5,9],other``."""
    m = re.match(r"([^\[,]+)(\[([^\]]+)\])?", nodelist)
    if not m:
        return nodelist.split(",")[0]
    prefix, _, body = m.groups()
    if not body:
        return prefix
    first = body.split(",")[0].split("-")[0]
    return prefix + first


def _from_slurm() -> Optional[DistributedConfig]:
    if "SLURM_PROCID" not in os.environ or "SLURM_NTASKS" not in os.environ:
        return None
    nproc = int(os.environ["SLURM_NTASKS"])
    pid = int(os.environ["SLURM_PROCID"])
    nodelist = os.environ.get(
        "SLURM_STEP_NODELIST", os.environ.get("SLURM_JOB_NODELIST", "localhost")
    )
    coord = f"{_expand_first_slurm_node(nodelist)}:{_DEFAULT_PORT}"
    return DistributedConfig(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
        source="env:SLURM",
    )


def _from_kubernetes() -> Optional[DistributedConfig]:
    """Kubernetes Indexed-Job resolution (reference
    ``KubernetesClusterResolver``, ``kubernetes_cluster_resolver.py:42``).

    The reference lists pods through the cluster API server; the TPU-native
    spelling needs no API access: an Indexed Job already gives every pod
    ``JOB_COMPLETION_INDEX`` (standard k8s env), the pod spec passes the
    replica count as ``TTD_K8S_REPLICAS``, and the coordinator address is
    either ``TTD_K8S_COORDINATOR`` or derived from the Indexed-Job +
    headless-service DNS convention ``<job>-0.<subdomain>`` via
    ``TTD_K8S_JOB_NAME`` / ``TTD_K8S_SUBDOMAIN``.
    """
    idx = os.environ.get("JOB_COMPLETION_INDEX")
    nproc = os.environ.get("TTD_K8S_REPLICAS")
    if idx is None or nproc is None:
        return None
    coord = os.environ.get("TTD_K8S_COORDINATOR")
    if not coord:
        job = os.environ.get("TTD_K8S_JOB_NAME")
        subdomain = os.environ.get("TTD_K8S_SUBDOMAIN")
        if not (job and subdomain):
            raise ValueError(
                "Kubernetes cluster env (JOB_COMPLETION_INDEX + "
                "TTD_K8S_REPLICAS) needs a coordinator: set "
                "TTD_K8S_COORDINATOR, or TTD_K8S_JOB_NAME + "
                "TTD_K8S_SUBDOMAIN for the <job>-0.<subdomain> headless-"
                "service convention")
        coord = f"{job}-0.{subdomain}:{_DEFAULT_PORT}"
    return DistributedConfig(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(idx),
        source="env:kubernetes",
    )


def _from_gce_metadata() -> Optional[DistributedConfig]:
    """GCE instance-group resolution (reference ``GCEClusterResolver``).

    The reference asks the GCE metadata server for the instance group's
    members per resolve; here a boot-time script does that fetch ONCE and
    injects the result as ``TTD_GCE_METADATA`` — inline JSON or ``@/path``
    to a JSON file — of the shape::

        {"instances": ["host-a", "host-b", ...],   # group members, ordered
         "self": "host-b",                         # this VM's name
         "port": 8476}                             # optional

    Resolution is pure env/file parsing: no egress, fully unit-testable.
    """
    raw = os.environ.get("TTD_GCE_METADATA")
    if not raw:
        return None
    try:
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        meta = json.loads(raw)
        instances = list(meta["instances"])
        self_name = meta["self"]
        port = int(meta.get("port", _DEFAULT_PORT))
        process_id = instances.index(self_name)
    except (json.JSONDecodeError, KeyError, ValueError, TypeError,
            OSError) as e:
        raise ValueError(
            f"Malformed TTD_GCE_METADATA (need a JSON object with an "
            f"instances list containing self, or @path to one): {e}") from e
    return DistributedConfig(
        coordinator_address=f"{instances[0]}:{port}",
        num_processes=len(instances),
        process_id=process_id,
        source="env:gce_metadata",
    )


def resolve_cluster(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> DistributedConfig:
    """Resolve this process's cluster position (see module docstring order)."""
    if any(v is not None for v in (coordinator_address, num_processes, process_id)):
        nproc = 1 if num_processes is None else num_processes
        pid = process_id or 0
        if not 0 <= pid < nproc:
            raise ValueError(
                f"process_id={pid} out of range for num_processes={nproc}; "
                "pass num_processes alongside process_id"
            )
        return DistributedConfig(
            coordinator_address=coordinator_address,
            num_processes=nproc,
            process_id=pid,
            source="explicit",
        )
    for probe in (_from_env_native, _from_tf_config, _from_slurm,
                  _from_kubernetes, _from_gce_metadata):
        cfg = probe()
        if cfg is not None:
            return cfg
    return DistributedConfig()


_initialized = False


def initialize_distributed(config: Optional[DistributedConfig] = None) -> DistributedConfig:
    """Initialize the JAX distributed runtime if the cluster is multi-process.

    Replaces the whole reference control plane: ``tf.train.Server`` startup,
    gRPC master/worker session setup, and collective group-key resolution
    (``collective_param_resolver_distributed.h``) are all subsumed by the XLA
    coordination service that ``jax.distributed.initialize`` connects to.
    Idempotent; safe to call in single-process mode (no-op).
    """
    global _initialized
    cfg = config or resolve_cluster()
    if cfg.is_multiprocess and not _initialized:
        import jax

        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
        _initialized = True
        logger.info(
            "jax.distributed initialized: process %d/%d via %s (source=%s)",
            cfg.process_id, cfg.num_processes, cfg.coordinator_address, cfg.source,
        )
    return cfg
