"""tensorflow_train_distributed_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of ``boyuanf/tensorflow_train_distributed``
(a GPU-only ``tf.distribute`` training harness: MirroredStrategy /
MultiWorkerMirroredStrategy over NCCL, ParameterServerStrategy, a Horovod hook,
and a DTensor 2-D-mesh stretch goal — see SURVEY.md §1–§3) designed TPU-first:

- one SPMD program per training job: ``jax.jit`` + ``NamedSharding`` over a
  ``jax.sharding.Mesh`` (the reference's strategy class hierarchy collapses into
  named mesh presets, see ``runtime.mesh``);
- XLA collectives over ICI/DCN replace the NCCL/gRPC cross-device-ops layer
  (reference: ``tensorflow/python/distribute/cross_device_ops.py``);
- a sharded host input pipeline with device prefetch replaces tf.data
  autoshard/rebatch (reference: ``tensorflow/python/distribute/input_lib.py``);
- orbax replaces ``tf.train.Checkpoint``/``CheckpointManager``;
- pallas kernels (flash/ring attention) provide the long-context path the
  reference lacked.

Public surface is re-exported here for convenience::

    import tensorflow_train_distributed_tpu as ttd
    mesh = ttd.build_mesh(ttd.MeshConfig(strategy="dp_tp"))
"""

from tensorflow_train_distributed_tpu.runtime.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    hybrid_shapes,
    strategy_preset,
    STRATEGY_PRESETS,
)
from tensorflow_train_distributed_tpu.runtime.distributed import (  # noqa: F401
    DistributedConfig,
    initialize_distributed,
    resolve_cluster,
)

__version__ = "0.1.0"
