"""The ``train_distributed`` launcher: flags → cluster → mesh → fit.

TPU-native rebuild of the reference's L6 entry point (SURVEY.md §2.1: a
``train_distributed`` CLI that parses ``--strategy`` / model selection,
builds ``TF_CONFIG``-aware cluster setup, and dispatches to a per-model
train fn).  The strategy zoo collapses into mesh presets
(``runtime.mesh.STRATEGY_PRESETS``), so the reference's launch contract
keeps working: ``--strategy=mirrored|multi_worker_mirrored|horovod|tpu``
all mean "data-parallel SPMD", ``--strategy=dtensor`` means the 2-D
data×tensor mesh, and ``TF_CONFIG`` in the environment still places this
process in the cluster (``runtime.distributed``).

Usage::

    train_distributed --config=resnet50_imagenet --steps=1000
    train_distributed --config=llama2_7b_sft --strategy=dp_tp \
        --mesh data=4,tensor=8 --precision=bfloat16 \
        --checkpoint-dir=/ckpt --checkpoint-every=500
    python -m tensorflow_train_distributed_tpu --config=mnist --steps=200
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
from typing import Optional, Sequence

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    from tensorflow_train_distributed_tpu.models import registry
    from tensorflow_train_distributed_tpu.runtime.mesh import STRATEGY_PRESETS

    p = argparse.ArgumentParser(
        prog="train_distributed",
        description="TPU-native distributed training launcher",
    )
    p.add_argument("--config", required=True,
                   help=f"model config; one of {registry.available()}")
    p.add_argument("--strategy", default=None,
                   choices=sorted(STRATEGY_PRESETS) + ["ps", "parameter_server"],
                   help="mesh preset (default: the config's preset); "
                        "reference names (mirrored/multi_worker_mirrored/"
                        "horovod/tpu/dtensor) are accepted")
    p.add_argument("--mesh", default=None, metavar="AXIS=N,...",
                   help="explicit mesh axis sizes overriding the preset, "
                        "e.g. data=4,tensor=2 (one axis may be -1)")
    p.add_argument("--dcn", default=None, metavar="AXIS=N,...",
                   help="multi-slice placement: how many slices divide each "
                        "axis over DCN, e.g. data=4 (default: all slices on "
                        "the outermost data-like axis)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--global-batch-size", type=int, default=None,
                   help="global batch size (default: the config's)")
    p.add_argument("--learning-rate", type=float, default=None)
    p.add_argument("--optimizer", default="adamw",
                   choices=["sgd", "momentum", "adam", "adamw", "lamb",
                            "adafactor"])
    p.add_argument("--weight-decay", type=float, default=0.0,
                   help="decoupled weight decay (adamw/lamb)")
    p.add_argument("--grad-clip-norm", type=float, default=None,
                   help="clip gradients to this global norm before the "
                        "optimizer update (default: the config's "
                        "convention, e.g. 1.0 for BERT/Llama; 0 disables)")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="LoRA fine-tuning for decoder-LM configs: freeze "
                        "the base, train rank-N adapters on "
                        "--lora-targets (0 = full fine-tuning). The "
                        "optimizer updates adapters only")
    p.add_argument("--lora-alpha", type=float, default=16.0,
                   help="LoRA scaling numerator (delta = alpha/rank·A·B)")
    p.add_argument("--lora-targets", default="query,value",
                   help="comma-separated Dense names to adapt (layers.py "
                        "names: query,key,value,out,wi_gate,wi_up,wo,"
                        "lm_head)")
    p.add_argument("--ema-decay", type=float, default=None,
                   help="track an exponential moving average of the "
                        "params in optimizer state (Polyak averaging — "
                        "the Keras ExponentialMovingAverage equivalent); "
                        "eval/--eval-only then score the EMA weights. "
                        "Typical: 0.999")
    p.add_argument("--warmup-steps", type=int, default=None,
                   help="linear LR warmup steps (default: the config's "
                        "warmup_ratio × --steps)")
    p.add_argument("--lr-schedule", default=None,
                   help="constant | warmup_cosine | warmup_linear | noam | "
                        "resnet_steps (default: the config's convention)")
    p.add_argument("--reduce-lr-factor", type=float, default=None,
                   help="enable ReduceLROnPlateau: multiply the LR by "
                        "this factor (0<f<1) when the monitored metric "
                        "plateaus (monitors val_loss when periodic eval "
                        "runs — --eval-every with --eval-steps — else "
                        "loss); requires a constant LR schedule")
    p.add_argument("--reduce-lr-patience", type=int, default=10,
                   help="plateau events before each reduction")
    p.add_argument("--reduce-lr-min", type=float, default=0.0,
                   help="LR floor for ReduceLROnPlateau")
    p.add_argument("--reduce-lr-cooldown", type=int, default=0,
                   help="events to skip after a reduction")
    p.add_argument("--precision", "--mixed-precision", dest="precision",
                   default="bfloat16",
                   help="dtype policy: float32 | bfloat16 | float16 "
                        "(Keras policy names mixed_bfloat16/mixed_float16 "
                        "also accepted)")
    p.add_argument("--steps-per-execution", type=int, default=1,
                   help="optimizer steps fused into one dispatch via an "
                        "inner scan (reference Model.fit arg of the same "
                        "name)")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatches per optimizer step (gradient "
                        "accumulation; reference analog: Horovod "
                        "backward_passes_per_step)")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--log-grad-norm", action="store_true",
                   help="add a grad_norm metric (pre-clip global norm of "
                        "the averaged grads) to step logs")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1: shard optimizer moments over the data "
                        "axis (N× less optimizer memory on an N-way dp "
                        "mesh; numerically identical)")
    p.add_argument("--grad-quant", default="none",
                   choices=["none", "f32", "int8"],
                   help="quantized gradient collectives (EQuARX): "
                        "explicit reduce-scatter → int8-quantize → "
                        "all-gather gradient exchange with an "
                        "error-feedback residual in the train state "
                        "(~4x less gradient wire traffic); 'f32' is "
                        "the explicit-pipeline exact baseline (A/B "
                        "leg), 'none' (default) today's implicit GSPMD "
                        "allreduce.  TTD_NO_GRAD_QUANT=1 forces none. "
                        "Composes with dp×fsdp / dp×tp meshes and "
                        "--grad-accum")
    p.add_argument("--grad-overlap", type=int, default=4, metavar="K",
                   help="with --grad-quant: partition the grad tree "
                        "into K byte-balanced buckets (reverse-backward "
                        "order) and dispatch each bucket's quantized "
                        "sync + optimizer apply in-flight while later "
                        "buckets compute (comm/compute overlap); 0 or "
                        "1 restores the sequential three-program "
                        "pipeline byte-for-byte.  TTD_NO_GRAD_OVERLAP=1 "
                        "forces sequential")
    p.add_argument("--sharded-update", action="store_true",
                   help="cross-replica sharded weight update (arxiv "
                        "2004.13336): each data replica runs the "
                        "optimizer on only its gradient shard, then "
                        "params are all-gathered — zero1 extended from "
                        "the moments to the update compute (implies "
                        "--zero1's moment shardings)")
    p.add_argument("--bleu-eval", type=int, default=0, metavar="N",
                   help="after training, beam-decode N eval batches and "
                        "report corpus BLEU (seq2seq/wmt configs only)")
    p.add_argument("--beam-size", type=int, default=4,
                   help="beam width for --bleu-eval (1 = greedy); WMT "
                        "convention is 4")
    p.add_argument("--bos-id", type=int, default=1)
    p.add_argument("--eos-id", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eval-steps", type=int, default=0,
                   help="run evaluation for N batches after training")
    p.add_argument("--eval-only", action="store_true",
                   help="restore from --checkpoint-dir and evaluate "
                        "--eval-steps batches without training "
                        "(Model.evaluate standalone)")
    p.add_argument("--eval-every", type=int, default=None,
                   help="also evaluate every N training steps (Keras "
                        "validation_freq analog); val_* metrics reach "
                        "callbacks/TensorBoard")
    p.add_argument("--data-dir", default=None,
                   help="train from an on-disk mmap corpus "
                        "(data.filesource.write_shards layout) instead of "
                        "the config's synthetic dataset")
    p.add_argument("--pack-seq", type=int, default=0, metavar="LEN",
                   help="treat --data-dir TFRecords as VARIABLE-length "
                        "tokenized documents (no feature spec needed) and "
                        "pack them into LEN-token rows with segment-masked "
                        "attention (decoder LM configs only)")
    p.add_argument("--pack-key", default="tokens",
                   help="feature name holding the document tokens under "
                        "--pack-seq")
    p.add_argument("--data-workers", type=int, default=0, metavar="N",
                   help="serve training batches from N out-of-process "
                        "workers PER HOST (the tf.data-service analog): "
                        "record read + decode/augment CPU work runs in "
                        "the workers, off the trainer's Python thread; "
                        "on a multi-host cluster each host runs its own "
                        "fleet serving its batch share (synthetic and "
                        "--data-dir sources)")
    p.add_argument("--data-transform", default=None,
                   help="named record transform for --data-dir (e.g. "
                        "u8_image_to_f32)")
    p.add_argument("--dataset-kwarg", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="override a config's synthetic-dataset kwarg "
                        "(repeatable; VALUE parsed as JSON, falling back "
                        "to string) — e.g. --dataset-kwarg image_size=64 "
                        "--dataset-kwarg num_examples=2048. Incompatible "
                        "with --data-dir")
    p.add_argument("--init-from-hf", default=None, metavar="DIR",
                   help="initialize a Llama- or BERT-family config's "
                        "params from a local HuggingFace checkpoint dir "
                        "(dims validated against the config/pipeline)")
    p.add_argument("--eval-split", type=float, default=0.0,
                   help="fraction of the dataset held out as a validation "
                        "split for --eval-every/--eval-steps (Keras "
                        "validation_split analog). 0 (default) evaluates "
                        "on the training distribution itself — train-set "
                        "monitoring only")
    # Checkpointing (reference: ModelCheckpoint + BackupAndRestore).
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--save-best", action="store_true",
                   help="also keep the best-metric checkpoint under "
                        "<checkpoint-dir>/best (Keras ModelCheckpoint "
                        "save_best_only analog; monitors val_loss when "
                        "periodic eval runs, else loss)")
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.add_argument("--max-to-keep", type=int, default=3)
    p.add_argument("--no-resume", action="store_true",
                   help="start fresh even if --checkpoint-dir has a "
                        "checkpoint")
    p.add_argument("--no-preemption-handler", action="store_true",
                   help="disable the SIGTERM-coordinated save-and-exit "
                        "(on by default when --checkpoint-dir is set)")
    p.add_argument("--watch-sigint", action="store_true",
                   help="treat SIGINT (Ctrl-C) like a preemption: "
                        "checkpoint, stop, exit with the preemption "
                        "code instead of a stack trace")
    # Self-healing supervision (runtime.supervisor): run training as a
    # child process, classify its exit (clean / preemption / crash),
    # relaunch with exponential backoff under a restart budget.  The
    # relaunch recovers through the normal auto-resume path, incl. the
    # crash-consistent restore fallback in training.checkpoint.
    p.add_argument("--supervise", action="store_true",
                   help="run training under the self-healing supervisor "
                        "(relaunch on crash/preemption; see MIGRATION "
                        "§fault tolerance)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="crash restart budget under --supervise "
                        "(preemption exits never consume it)")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   help="base crash-relaunch delay; doubles per "
                        "consecutive crash")
    p.add_argument("--restart-backoff-max", type=float, default=60.0,
                   help="cap on the crash-relaunch delay")
    p.add_argument("--restart-window", type=float, default=0.0,
                   help="rolling window (seconds) for the crash budget: "
                        "only crashes within it count against "
                        "--max-restarts, so a correlated burst cannot "
                        "permanently exhaust a long run's protection "
                        "(0 = lifetime accounting)")
    p.add_argument("--restart-jitter", type=float, default=0.1,
                   help="jitter the crash backoff UP by up to this "
                        "fraction of itself (decorrelates fleet-wide "
                        "relaunch stampedes; 0 disables)")
    p.add_argument("--no-elastic", action="store_true",
                   help="treat device-loss exits as plain crashes "
                        "instead of relaunching onto the surviving "
                        "devices with the checkpoint resharded "
                        "(TTD_NO_ELASTIC=1 is the env equivalent)")
    p.add_argument("--max-device-losses", type=int, default=16,
                   help="give up after this many device-loss relaunches "
                        "(they are crash-budget-free, but a mesh can "
                        "only shrink so many times — a flapping chip "
                        "must not relaunch forever)")
    p.add_argument("--no-restart-on-preemption", action="store_true",
                   help="hand the preemption exit code to the caller "
                        "instead of relaunching (external scheduler "
                        "owns the restart)")
    p.add_argument("--supervisor-journal", default=None,
                   help="JSON-lines attempt journal (default: "
                        "<checkpoint-dir>/supervisor.jsonl)")
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="ARM deterministic fault injection "
                        "(runtime.faults grammar, e.g. "
                        "'step:200:kill9;ckpt:save:partial:step=40'); "
                        "also via TTD_FAULT_PLAN — chaos testing only")
    # Observability.
    p.add_argument("--tensorboard-dir", default=None)
    p.add_argument("--jsonl-log", default=None,
                   help="append per-step metrics as JSON lines to this file")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace into this directory "
                        "(reference: TensorBoard callback profile_batch)")
    p.add_argument("--profile-steps", default="10,20", metavar="START,STOP",
                   help="step window for --profile-dir")
    p.add_argument("--profiler-port", type=int, default=None,
                   help="start an on-demand profiler server on this port "
                        "(reference: tf.profiler.experimental.server.start; "
                        "capture from TensorBoard's Capture Profile dialog)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="warn + dump thread stacks if no step completes in "
                        "this many seconds (reference: coordinator "
                        "watchdog); 0 disables")
    # Cluster placement (reference: TF_CONFIG / cluster resolvers; these
    # flags take precedence, then TTD_*/TF_CONFIG/SLURM env, see
    # runtime.distributed.resolve_cluster).
    p.add_argument("--coordinator-address", default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a jax backend (cpu useful with "
                        "--cpu-devices for local testing)")
    p.add_argument("--cpu-devices", type=int, default=None,
                   help="with --platform=cpu: number of virtual devices")
    p.add_argument("--list-configs", action="store_true",
                   help="print available configs and exit")
    return p


def _parse_mesh_overrides(spec: str) -> dict[str, int]:
    from tensorflow_train_distributed_tpu.runtime.mesh import AXES

    sizes: dict[str, int] = {}
    for part in spec.split(","):
        if not part:
            continue
        axis, _, val = part.partition("=")
        axis = axis.strip()
        if axis not in AXES:
            raise ValueError(f"Unknown mesh axis {axis!r}; axes: {AXES}")
        sizes[axis] = int(val)
    return sizes


def _resolve_schedule(args, entry):
    """(schedule_name, warmup_steps) from flags + config conventions —
    the ONE place this defaulting lives (validation and the optimizer
    builder must agree)."""
    name = args.lr_schedule or entry.get("lr_schedule", "constant")
    warmup = args.warmup_steps
    if warmup is None:
        warmup = int(entry.get("warmup_ratio", 0.0) * args.steps)
    return name, warmup


def _validate_constant_lr(args, entry):
    name, warmup = _resolve_schedule(args, entry)
    if name != "constant" or warmup:
        raise SystemExit(
            "--reduce-lr-factor needs a constant LR (no schedule/"
            f"warmup): got schedule={name!r}, warmup={warmup} — a "
            "schedule and metric-driven reduction would fight over "
            "the same knob")


def _eval_view(args, state):
    """The state eval should score: the EMA weights when --ema-decay is
    on (a read-only swapped view; training continues from ``state``)."""
    if getattr(args, "ema_decay", None) is not None:
        from tensorflow_train_distributed_tpu.training.ema import (
            swap_ema_params,
        )

        return swap_ema_params(state)
    return state


def _make_optimizer(args, entry):
    """(optimizer, lr_schedule) from flags + the config's LR convention."""
    import optax

    from tensorflow_train_distributed_tpu.training import schedules

    peak = args.learning_rate
    if peak is None:
        peak = entry["learning_rate"]
    name, warmup = _resolve_schedule(args, entry)
    lr = schedules.by_name(name, peak, args.steps, warmup_steps=warmup)
    wrap = False
    if getattr(args, "reduce_lr_factor", None) is not None:
        # ReduceLROnPlateau needs the LR to live in optimizer STATE, not
        # baked into a schedule closure: inject_hyperparams puts it
        # there, and the callback rewrites it functionally between steps.
        _validate_constant_lr(args, entry)  # run() checks early; re-check
        wrap, lr = True, peak

    def build(fn, **kw):
        if wrap:
            # kwargs only: inject_hyperparams injects keyword args.
            return optax.inject_hyperparams(fn)(learning_rate=lr, **kw)
        return fn(lr, **kw)

    if args.optimizer == "sgd":
        tx = build(optax.sgd)
    elif args.optimizer == "momentum":
        tx = build(optax.sgd, momentum=0.9, nesterov=True)
    elif args.optimizer == "adam":
        tx = build(optax.adam)
    elif args.optimizer == "lamb":
        # BERT large-batch convention (the reference's PS-pretrain config
        # scaled with LAMB); layerwise trust ratios make the global batch
        # scalable far past Adam's stability range.
        tx = build(optax.lamb, weight_decay=args.weight_decay)
    elif args.optimizer == "adafactor":
        # Memory-frugal second-moment factorization — the optimizer of
        # choice when optimizer state must not double 7B-param HBM use.
        tx = build(optax.adafactor,
                   weight_decay_rate=args.weight_decay or None)
    else:
        tx = build(optax.adamw, weight_decay=args.weight_decay)
    clip = args.grad_clip_norm
    if clip is None:
        clip = entry.get("grad_clip_norm")
    if clip is not None and clip < 0:
        raise ValueError(
            f"--grad-clip-norm must be >= 0 (0 disables), got {clip}; a "
            "negative max norm would flip every update's sign")
    if clip:  # 0/None = disabled
        # Applied to the already-unscaled, globally-averaged grads (the
        # Trainer unscales before tx), so the clip norm means the same
        # thing at any loss-scale or batch size.
        tx = optax.chain(optax.clip_by_global_norm(clip), tx)
    if getattr(args, "lora_rank", 0):
        # Adapters-only updates AND optimizer state; applied after the
        # clip chain so the global norm is over adapter grads.  (The CLI
        # rejects combining with --ema-decay — a full-params EMA defeats
        # LoRA's memory point — so the EMA wrap below never composes
        # with this in practice.)
        from tensorflow_train_distributed_tpu.models.lora import (
            freeze_base,
        )

        tx = freeze_base(tx)
    if getattr(args, "ema_decay", None) is not None:
        from tensorflow_train_distributed_tpu.training.ema import (
            wrap_with_ema,
        )

        # Range validation (incl. the 0.0 and 1.0 edges) lives in
        # ema_of_params — one source of truth.
        tx = wrap_with_ema(tx, args.ema_decay)
    # Under ReduceLROnPlateau the LR is optimizer STATE, not a schedule —
    # there is no step->lr function for the observational metric.
    return tx, (None if wrap else lr)


def _bleu_eval(args, task, state, loader) -> float:
    """Beam-decode eval batches and score corpus BLEU — the reference's
    Transformer-big target metric ([SPEC] config[3]), evaluated the WMT
    way (beam search + length penalty) rather than teacher-forced."""
    import numpy as np

    from tensorflow_train_distributed_tpu.models import transformer as tr
    from tensorflow_train_distributed_tpu.ops.metrics import (
        corpus_bleu, strip_after_eos,
    )

    if not isinstance(task, tr.Seq2SeqTask):
        raise ValueError(
            "--bleu-eval needs a seq2seq config (wmt family); "
            f"{type(task).__name__} does not decode")
    hyps, refs = [], []
    for _, batch in zip(range(args.bleu_eval), loader):
        out = np.asarray(tr.beam_translate(
            task.config, state.params, batch["inputs"],
            max_len=batch["targets_out"].shape[1],
            beam_size=args.beam_size, bos_id=args.bos_id,
            eos_id=args.eos_id))
        # Padded eval rows (sample_weight 0) are duplicates of a real
        # record — scoring them would double-count sentences.
        keep = (np.asarray(batch["sample_weight"]) > 0
                if "sample_weight" in batch
                else np.ones(len(out), bool))
        hyps += [strip_after_eos(list(r), args.eos_id)
                 for r, k in zip(out, keep) if k]
        refs += [strip_after_eos(list(r), args.eos_id)
                 for r, k in zip(np.asarray(batch["targets_out"]), keep)
                 if k]
    return corpus_bleu(hyps, refs)


@dataclasses.dataclass
class RunResult:
    """What a launch produced (returned by ``run`` for tests/embedding)."""

    state: object
    history: dict
    eval_metrics: Optional[dict]
    mesh: object
    preempted: bool = False


def _parse_profile_steps(spec: str) -> tuple[int, int]:
    try:
        start, stop = (int(p) for p in spec.split(","))
        return start, stop
    except ValueError:
        raise SystemExit(
            f"--profile-steps expects START,STOP (two integers), got "
            f"{spec!r}") from None


def _dataset_kwargs(entry: dict, args: argparse.Namespace) -> dict:
    """Registry dataset kwargs with ``--dataset-kwarg KEY=VALUE``
    overrides (VALUE parsed as JSON so ints/floats/bools arrive typed;
    non-JSON stays a string)."""
    import json

    kw = dict(entry["dataset_kwargs"])
    for item in args.dataset_kwarg:
        key, sep, raw = item.partition("=")
        if not sep or not key:
            raise SystemExit(
                f"--dataset-kwarg wants KEY=VALUE, got {item!r}")
        try:
            kw[key] = json.loads(raw)
        except ValueError:
            kw[key] = raw
    return kw


def run(args: argparse.Namespace) -> RunResult:
    """Build the full stack from parsed flags and train."""
    import jax

    from tensorflow_train_distributed_tpu.runtime import faults

    # Chaos testing: arm the fault plan (flag wins over TTD_FAULT_PLAN)
    # before anything expensive so a typo'd spec dies immediately.
    if getattr(args, "fault_plan", None):
        faults.arm(args.fault_plan, seed=args.seed)
    elif faults.arm_from_env(seed=args.seed) is None:
        # No plan for THIS run: clear any plan a previous in-process
        # run() armed, or its stale entries would fire into this one.
        faults.disarm()

    # Flag-vs-flag errors are decidable before the expensive setup
    # (checkpoint restore, HF import, mesh build) — fail now.
    if args.eval_only and args.eval_steps <= 0:
        raise SystemExit("--eval-only needs --eval-steps N (>0)")
    if args.save_best and not args.checkpoint_dir:
        raise SystemExit("--save-best needs --checkpoint-dir")
    if args.data_workers > 0 and args.pack_seq:
        raise SystemExit(
            "--data-workers does not compose with --pack-seq yet "
            "(packing runs in-process); drop one of the flags")
    if args.data_workers > 0 and args.eval_split:
        raise SystemExit(
            "--data-workers does not compose with --eval-split: the "
            "worker fleet streams the FULL dataset, so training would "
            "consume the held-out examples (contaminated validation); "
            "drop one of the flags")
    if args.data_workers > 0:
        from tensorflow_train_distributed_tpu.models import registry as _r

        _gb = args.global_batch_size
        if _gb is None:
            _gb = _r.get_entry(args.config)["global_batch_size"]
        if _gb % args.data_workers:
            raise SystemExit(
                f"global batch {_gb} not divisible by "
                f"--data-workers={args.data_workers} (each worker serves "
                "an equal slice of every batch)")
    if args.reduce_lr_factor is not None:
        if not 0.0 < args.reduce_lr_factor < 1.0:
            raise SystemExit(
                f"--reduce-lr-factor must be in (0, 1), got "
                f"{args.reduce_lr_factor}")
        from tensorflow_train_distributed_tpu.models import registry as _reg

        _validate_constant_lr(args, _reg.get_entry(args.config))

    # Elastic relaunch (runtime.supervisor): after a device-loss exit
    # the supervisor pins the surviving device count; the relaunched
    # child shrinks its virtual CPU platform (or slices the real device
    # list below) and lets the mesh preset re-resolve on the survivors.
    import os as _os

    from tensorflow_train_distributed_tpu.runtime.supervisor import (
        ENV_ELASTIC_DEVICES,
    )

    elastic_devices = None
    _elastic_env = _os.environ.get(ENV_ELASTIC_DEVICES)
    if _elastic_env:
        try:
            elastic_devices = int(_elastic_env)
        except ValueError:
            raise SystemExit(
                f"{ENV_ELASTIC_DEVICES}={_elastic_env!r}: device count "
                "must be an integer") from None
        if elastic_devices < 1:
            raise SystemExit(
                f"{ENV_ELASTIC_DEVICES}={_elastic_env!r}: device count "
                "must be >= 1")
        if args.cpu_devices:
            args.cpu_devices = min(args.cpu_devices, elastic_devices)
            logger.warning(
                "elastic relaunch: virtual CPU platform shrunk to %d "
                "device(s) (%s)", args.cpu_devices, ENV_ELASTIC_DEVICES)

    if args.platform or args.cpu_devices:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform, args.cpu_devices)

    from tensorflow_train_distributed_tpu.data.datasets import get_dataset
    from tensorflow_train_distributed_tpu.data.pipeline import (
        DataConfig, HostDataLoader,
    )
    from tensorflow_train_distributed_tpu.models import registry
    from tensorflow_train_distributed_tpu.runtime.distributed import (
        initialize_distributed, resolve_cluster,
    )
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh, strategy_preset,
    )
    from tensorflow_train_distributed_tpu.training import (
        History, JsonlLogger, Policy, ProgressLogger, TensorBoardScalars,
        Trainer, TrainerConfig,
    )
    from tensorflow_train_distributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    # 1. Cluster: flags → env (TTD_* / TF_CONFIG / SLURM) → single-process.
    cluster = resolve_cluster(
        coordinator_address=args.coordinator_address,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    initialize_distributed(cluster)

    # 2. Mesh from strategy preset (+ explicit axis overrides).
    entry = registry.get_entry(args.config)
    strategy = args.strategy or entry["strategy"]
    devices = list(jax.devices())
    if elastic_devices is not None and elastic_devices < len(devices):
        # Real-backend elastic relaunch: the dead chips may still be
        # enumerable for a while — pin the mesh to the surviving count.
        # KNOWN APPROXIMATION: the sidecar carries a COUNT, not device
        # ids, so the prefix slice can pick a still-enumerable dead
        # chip (and drop a healthy one) when the runtime keeps listing
        # it.  That relaunch exits 113 again and the supervisor's
        # max_device_losses cap bounds the loop; identifying survivors
        # by id/health-probe is the multi-host elasticity seam
        # (ROADMAP) — the virtual-CPU path shrinks the platform itself,
        # so the slice is exact there.
        devices = devices[:elastic_devices]
        logger.warning(
            "elastic relaunch: building the mesh over %d of %d "
            "visible device(s)", len(devices), len(jax.devices()))
    n_dev = len(devices)
    cfg = strategy_preset(strategy, n_dev)
    if args.mesh:
        overrides = _parse_mesh_overrides(args.mesh)
        sizes = cfg.axis_sizes()
        sizes.update(overrides)
        if -1 not in sizes.values() and "data" not in overrides:
            sizes["data"] = -1  # let data absorb the remaining devices
        cfg = MeshConfig(strategy=strategy, **sizes)
    if elastic_devices is not None:
        # Divisibility degrade: explicit --mesh sizes pinned for the
        # original device count shrink to the nearest valid layout on
        # the survivors instead of crash-looping the relaunch.
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            degrade_to_fit,
        )

        fitted = degrade_to_fit(cfg, n_dev)
        if fitted.axis_sizes() != cfg.axis_sizes():
            logger.warning(
                "elastic relaunch: mesh %s does not fit %d device(s); "
                "degraded to %s", cfg.axis_sizes(), n_dev,
                fitted.axis_sizes())
        cfg = fitted
    dcn_axes = _parse_mesh_overrides(args.dcn) if args.dcn else None
    mesh = build_mesh(cfg, devices=devices, dcn_axes=dcn_axes)
    logger.info("mesh: %s (strategy=%s, %d devices)",
                dict(mesh.shape), strategy, n_dev)

    # 3. Data: sharded host loader over this config's dataset.  With
    # --eval-split, a held-out tail becomes the validation source (Keras
    # validation_split semantics); otherwise eval runs on the training
    # distribution (documented train-set monitoring).
    global_batch = args.global_batch_size or entry["global_batch_size"]
    if args.pack_seq and not args.data_dir:
        raise SystemExit("--pack-seq needs --data-dir (a varlen TFRecord "
                         "corpus to pack)")
    if args.dataset_kwarg and args.data_dir:
        raise SystemExit("--dataset-kwarg overrides the config's SYNTHETIC "
                         "dataset; it has no effect with --data-dir")
    # Pure service mode: the workers own ALL record I/O — building the
    # in-process source too would re-materialize/re-index the corpus in
    # the trainer for nothing.  Any in-process consumer (eval, BLEU, HF
    # sample, checkpoint-resume sample) keeps the source.
    service_only = (args.data_workers > 0 and args.eval_steps <= 0
                    and args.bleu_eval <= 0 and args.init_from_hf is None
                    and args.checkpoint_dir is None)
    if service_only:
        source = None
        dir_kind = None
        if args.data_dir:
            import pathlib

            _root = pathlib.Path(args.data_dir)
            dir_kind = ("tfrecord_dir"
                        if any(_root.glob("*.tfrecord"))
                        or any(_root.glob("*.tfrecord.gz"))
                        else "array_dir")
    elif args.data_dir:
        # Autodetect format: a dir of *.tfrecord files (the reference's
        # tf.data corpus convention) vs the native mmap part-*/ layout.
        import pathlib

        data_root = pathlib.Path(args.data_dir)
        if args.pack_seq:
            # Varlen documents → packed LM rows (decoder configs).
            from tensorflow_train_distributed_tpu.data.packing import (
                PackedLmSource,
            )
            from tensorflow_train_distributed_tpu.data.tfrecord import (
                TFRecordSource,
            )

            if args.data_transform:
                raise SystemExit(
                    "--data-transform does not apply under --pack-seq "
                    "(packing consumes raw token documents); drop one of "
                    "the two flags")
            paths = sorted([*data_root.glob("*.tfrecord"),
                            *data_root.glob("*.tfrecord.gz")])
            if not paths:
                raise SystemExit(
                    f"--pack-seq needs *.tfrecord(.gz) files under "
                    f"{data_root}")
            source = PackedLmSource.from_source(
                TFRecordSource(paths), args.pack_seq, key=args.pack_key)
            # Fail at launch: only decoder LM tasks consume packed
            # batches, and clamped out-of-vocab ids would train on
            # garbage with a finite loss (the --init-from-hf hazard).
            from tensorflow_train_distributed_tpu.models.llama import (
                CausalLmTask,
            )
            from tensorflow_train_distributed_tpu.models.moe import (
                MoeLmTask,
            )

            probe_task = entry["task_factory"]()
            if not isinstance(probe_task, (CausalLmTask, MoeLmTask)):
                raise SystemExit(
                    f"--pack-seq needs a decoder LM config (llama or moe "
                    f"family); {type(probe_task).__name__} does not "
                    "consume packed batches")
            max_id = source.max_token_id  # tracked at pack time, O(1) here
            if max_id >= probe_task.config.vocab_size:
                raise SystemExit(
                    f"packed corpus has token id {max_id} but the "
                    f"config's vocab is {probe_task.config.vocab_size}; "
                    "re-tokenize or pick a matching config "
                    "(out-of-range ids would clamp and train on garbage)")
        else:
            dir_kind = ("tfrecord_dir"
                        if any(data_root.glob("*.tfrecord"))
                        or any(data_root.glob("*.tfrecord.gz"))
                        else "array_dir")
            source = get_dataset(dir_kind, root=args.data_dir,
                                 transform=args.data_transform)
    else:
        dir_kind = None
        source = get_dataset(entry["dataset"], **_dataset_kwargs(entry, args))
    service_spec = None
    if args.data_workers > 0:
        # pack-seq already rejected at arg validation; multiprocess is
        # only known after cluster resolution, so it lands here.
        from tensorflow_train_distributed_tpu.data.service import SourceSpec

        if cluster.is_multiprocess:
            # Per-host worker fleets: every process runs its own
            # dispatcher; worker w of host h autoshard-slices as process
            # h*W+w of H*W (reference tf.data service over a cluster).
            shards = cluster.num_processes * args.data_workers
            if global_batch % shards:
                raise SystemExit(
                    f"--global-batch-size={global_batch} must divide by "
                    f"num_hosts*data_workers={shards} (each worker "
                    "serves one equal slice)")
        if args.data_dir:
            service_spec = SourceSpec(
                dir_kind, {"root": args.data_dir,
                           "transform": args.data_transform})
        else:
            service_spec = SourceSpec(entry["dataset"],
                                      _dataset_kwargs(entry, args))
    eval_source = source
    if (args.eval_steps > 0 or args.bleu_eval > 0) and not args.eval_split:
        # Keras validation_data semantics imply HELD-OUT data; without
        # --eval-split the val_* numbers measure the training
        # distribution — fine for smoke runs, misleading for model
        # selection. Say so loudly rather than silently (VERDICT r2).
        logger.warning(
            "evaluation will run on the TRAINING distribution (no "
            "--eval-split): val_* metrics are not held-out generalization "
            "numbers; pass --eval-split F (e.g. 0.1) to hold out a split")
    if args.eval_split:
        if args.eval_steps <= 0:
            raise SystemExit(
                "--eval-split without --eval-steps N (>0) would hold out "
                "data that is never evaluated; add --eval-steps (and "
                "optionally --eval-every)")
        from tensorflow_train_distributed_tpu.data.datasets import (
            train_val_split,
        )

        source, eval_source = train_val_split(
            source, args.eval_split, min_val=global_batch,
            min_train=global_batch)
    loader = None if source is None else HostDataLoader(
        source,
        DataConfig(global_batch_size=global_batch, seed=args.seed),
        process_index=cluster.process_id if cluster.is_multiprocess else None,
        process_count=cluster.num_processes if cluster.is_multiprocess else None,
    )

    def make_eval_loader():
        # Fresh single-pass loader per eval so every run sees the same
        # records in the same (seeded) order.  drop_remainder=False: the
        # final partial batch is padded and weight-masked so a finite
        # split's metrics cover every example exactly (Task sample_weight
        # contract); training keeps whole batches.
        eval_loader = HostDataLoader(
            eval_source,
            DataConfig(global_batch_size=global_batch, seed=args.seed + 1,
                       num_epochs=1, drop_remainder=False),
            process_index=(cluster.process_id
                           if cluster.is_multiprocess else None),
            process_count=(cluster.num_processes
                           if cluster.is_multiprocess else None),
        )
        if 0 < eval_loader.steps_per_epoch() < args.eval_steps:
            logger.warning(
                "--eval-steps=%d exceeds the evaluation source's %d "
                "batches/epoch; each eval averages over the smaller count",
                args.eval_steps, eval_loader.steps_per_epoch())
        return eval_loader

    # 4. Trainer: task + optimizer + policy + callbacks.
    task = entry["task_factory"]()
    if args.lora_rank:
        from tensorflow_train_distributed_tpu.models.llama import (
            CausalLmTask,
        )
        from tensorflow_train_distributed_tpu.models.lora import (
            LoraSpec, validate_targets,
        )

        if not isinstance(task, CausalLmTask):
            raise SystemExit(
                f"--lora-rank applies to decoder-LM configs; "
                f"{args.config!r} is not one")
        if args.ema_decay is not None:
            raise SystemExit(
                "--ema-decay with --lora-rank is not supported: the EMA "
                "would keep a full f32 copy of the FROZEN base (whose "
                "average never moves) — defeating LoRA's memory point at "
                "exactly the scale LoRA exists for")
        try:
            spec = LoraSpec(
                rank=args.lora_rank, alpha=args.lora_alpha,
                targets=validate_targets(args.lora_targets.split(",")))
        except ValueError as e:
            raise SystemExit(str(e))
        task = CausalLmTask(dataclasses.replace(task.config, lora=spec))
        logger.info("LoRA enabled: rank=%d alpha=%.1f targets=%s (base "
                    "frozen)", spec.rank, spec.alpha, spec.targets)
        if args.checkpoint_dir:
            # Self-describing checkpoints: alpha is not recoverable from
            # weights, and serving/merging with a retyped-wrong spec is
            # silent corruption — sample.py / export read this sidecar.
            from tensorflow_train_distributed_tpu.models.lora import (
                load_spec, save_spec,
            )

            prior = load_spec(args.checkpoint_dir)
            if prior is not None and prior != spec:
                # A resume with mistyped flags must not silently rewrite
                # the authoritative record (alpha shape-checks nothing).
                raise SystemExit(
                    f"--lora-* flags {spec} disagree with the existing "
                    f"lora_spec.json {prior} in --checkpoint-dir — fix "
                    "the flags to resume, or use a fresh dir")
            save_spec(args.checkpoint_dir, spec)
    elif args.checkpoint_dir:
        from tensorflow_train_distributed_tpu.models.lora import load_spec

        stale = load_spec(args.checkpoint_dir)
        if stale is not None:
            raise SystemExit(
                f"--checkpoint-dir carries lora_spec.json ({stale}) from "
                "a LoRA run, but this run has no --lora-rank: pass the "
                "matching --lora-* flags to resume it, or use a fresh "
                "checkpoint dir (a stale sidecar would make sample.py "
                "mis-serve the new checkpoint)")
    if args.bleu_eval > 0:
        # Fail at launch, not after a multi-hour run completes.
        from tensorflow_train_distributed_tpu.models import transformer as tr

        if not isinstance(task, tr.Seq2SeqTask):
            raise ValueError(
                "--bleu-eval needs a seq2seq config (wmt family); "
                f"{type(task).__name__} does not decode")
    policy = Policy.from_name(args.precision)
    callbacks = [History(), ProgressLogger(examples_per_step=global_batch)]
    # val_loss only reaches step events when PERIODIC eval runs during
    # fit (--eval-every); --eval-steps alone evaluates after training.
    # Shared by ReduceLROnPlateau and BestCheckpoint — the pair must
    # watch the same signal to behave coherently.
    monitor = ("val_loss"
               if args.eval_every and args.eval_steps > 0 else "loss")
    if args.reduce_lr_factor is not None:
        from tensorflow_train_distributed_tpu.training import (
            ReduceLROnPlateau,
        )

        callbacks.append(ReduceLROnPlateau(
            monitor=monitor,
            factor=args.reduce_lr_factor,
            patience=args.reduce_lr_patience,
            min_lr=args.reduce_lr_min,
            cooldown=args.reduce_lr_cooldown))
    if args.tensorboard_dir:
        callbacks.append(TensorBoardScalars(args.tensorboard_dir))
    if args.jsonl_log:
        callbacks.append(JsonlLogger(args.jsonl_log))
    if args.profile_dir:
        from tensorflow_train_distributed_tpu.runtime.profiling import (
            ProfileCallback,
        )

        start, stop = _parse_profile_steps(args.profile_steps)
        callbacks.append(ProfileCallback(
            args.profile_dir, start_step=start, stop_step=stop))
    if args.profiler_port:
        from tensorflow_train_distributed_tpu.runtime.profiling import (
            start_profiler_server,
        )

        start_profiler_server(args.profiler_port)
    if args.stall_timeout > 0:
        from tensorflow_train_distributed_tpu.training import StallWatchdog

        callbacks.append(StallWatchdog(args.stall_timeout))
    ckpt = None
    watcher = None
    if args.checkpoint_dir:
        ckpt = CheckpointManager(
            args.checkpoint_dir, max_to_keep=args.max_to_keep)
        if args.save_best:
            import os as _os

            from tensorflow_train_distributed_tpu.training.callbacks import (
                BestCheckpoint,
            )

            callbacks.append(BestCheckpoint(
                _os.path.join(args.checkpoint_dir, "best"),
                monitor=monitor))
        if not args.no_preemption_handler:
            from tensorflow_train_distributed_tpu.runtime.preemption import (
                PreemptionCheckpointCallback, PreemptionWatcher,
            )

            try:
                watcher = PreemptionWatcher(
                    watch_sigint=getattr(args, "watch_sigint", False),
                ).install()
            except RuntimeError:  # not on the main thread (embedded use)
                watcher = None
            if watcher is not None:
                callbacks.append(PreemptionCheckpointCallback(watcher))
    optimizer, lr_schedule = _make_optimizer(args, entry)
    trainer = Trainer(
        task,
        optimizer,
        mesh,
        lr_schedule=lr_schedule,
        policy=policy,
        config=TrainerConfig(
            seed=args.seed,
            steps_per_execution=args.steps_per_execution,
            grad_accum=args.grad_accum,
            log_every=args.log_every,
            checkpoint_every=args.checkpoint_every,
            log_grad_norm=args.log_grad_norm,
            zero1=args.zero1,
            grad_quant=args.grad_quant,
            grad_overlap=args.grad_overlap,
            sharded_update=args.sharded_update,
            # Mid-training eval (--eval-every) must score the SAME model
            # the final eval/export does: the EMA view when enabled.
            eval_state_view=(
                (lambda s: _eval_view(args, s))
                if args.ema_decay is not None else None),
        ),
        callbacks=callbacks,
        checkpoint_manager=ckpt,
    )

    service = None
    try:
        # 5. Resume (reference BackupAndRestore): restore latest if present.
        state = None
        if (ckpt is not None and not args.no_resume
                and ckpt.latest_step() is not None):
            sample = next(iter(loader))
            template = trainer.create_state(sample)
            # restore() may fall back past quarantined torn saves — or
            # come back empty when EVERY retained step was corrupt; the
            # relaunch then starts fresh from the init rather than
            # crash-looping (the supervisor contract).
            state = ckpt.restore(template)
            if state is None:
                logger.error(
                    "no restorable checkpoint in %s (all retained steps "
                    "quarantined); starting fresh", args.checkpoint_dir)
                state = template
            else:
                logger.info("resumed from step %d", int(state.step))
        elif args.init_from_hf:
            # SFT entry point: start from a local HF Llama checkpoint
            # (models.import_hf) instead of random init; a later resume
            # from --checkpoint-dir takes precedence over re-importing.
            from tensorflow_train_distributed_tpu.models import import_hf
            from tensorflow_train_distributed_tpu.models.bert import (
                BertConfig,
            )
            from tensorflow_train_distributed_tpu.models.llama import (
                LlamaConfig,
            )

            from tensorflow_train_distributed_tpu.models.moe import (
                MoeConfig,
            )

            task_cfg = getattr(task, "config", None)
            sample = None
            if isinstance(task_cfg, MoeConfig):
                # Sparse-MoE checkpoints: Mixtral, or Qwen2-MoE when
                # the checkpoint says so — import_moe dispatches on the
                # checkpoint's model_type (AutoConfig: local dirs AND
                # hub ids, no weights downloaded before the decision);
                # capacity_factor E/k on import makes routing exactly
                # HF's (import_hf).
                hf_cfg, hf_params = import_hf.import_moe(
                    args.init_from_hf, config=task_cfg)
            elif isinstance(task_cfg, LlamaConfig):
                # The task's config decides the param-tree layout (scan
                # vs per-layer) and validates dims vs the checkpoint.
                hf_cfg, hf_params = import_hf.import_llama(
                    args.init_from_hf, config=task_cfg)
            elif isinstance(task_cfg, BertConfig):
                # BERT import derives its own HF-compat config (bias/
                # token-type/embed-LN knobs); rebuild the task around it
                # so the model matches the imported tree — but the
                # checkpoint must still cover the data pipeline's token
                # space and sequence length (a smaller embedding table
                # would CLAMP out-of-range ids in XLA's gather and train
                # on garbage with a finite loss).
                from tensorflow_train_distributed_tpu.models.bert import (
                    BertMlmTask,
                )

                hf_cfg, hf_params = import_hf.import_bert(args.init_from_hf)
                sample = next(iter(loader))
                if hf_cfg.vocab_size < task_cfg.vocab_size:
                    raise SystemExit(
                        f"HF checkpoint vocab ({hf_cfg.vocab_size}) is "
                        f"smaller than the config's ({task_cfg.vocab_size})"
                        " — token ids would silently clamp")
                if hf_cfg.max_positions < sample["input_ids"].shape[1]:
                    raise SystemExit(
                        f"HF checkpoint max_positions "
                        f"({hf_cfg.max_positions}) < the pipeline's "
                        f"sequence length ({sample['input_ids'].shape[1]})")
                task = BertMlmTask(hf_cfg)
                trainer.task = task
            else:
                raise SystemExit(
                    f"--init-from-hf supports Llama-, Mixtral- and "
                    f"BERT-family --config; {args.config!r} is none of "
                    "these")
            if sample is None:
                sample = next(iter(loader))
            state = trainer.create_state(sample, params=hf_params)
            logger.info("initialized from HF checkpoint %s (%d layers)",
                        args.init_from_hf, hf_cfg.num_layers)

        if args.eval_only:
            if state is None:
                raise SystemExit(
                    "--eval-only needs a restorable checkpoint "
                    "(--checkpoint-dir with a saved state) or "
                    "--init-from-hf")
            eval_metrics = trainer.evaluate(
                make_eval_loader(), _eval_view(args, state),
                steps=args.eval_steps)
            logger.info("eval-only: %s", eval_metrics)
            if args.bleu_eval > 0:
                bleu = _bleu_eval(args, task, _eval_view(args, state),
                                  make_eval_loader())
                eval_metrics = dict(eval_metrics or {}, bleu=bleu)
                logger.info("BLEU (beam %d, %d batches): %.2f",
                            args.beam_size, args.bleu_eval, bleu)
            history = next(
                (c.history for c in callbacks if isinstance(c, History)),
                {})
            return RunResult(state=state, history=history,
                             eval_metrics=eval_metrics, mesh=mesh,
                             preempted=False)

        remaining = args.steps - (0 if state is None else int(state.step))
        k = args.steps_per_execution
        if remaining > 0 and remaining % k:
            # Off-cycle resume (checkpoint step not a multiple of k) or
            # steps not divisible by k: round up rather than crashloop.
            rounded = -(-remaining // k) * k
            logger.warning(
                "remaining steps %d not a multiple of "
                "steps_per_execution=%d; training %d steps",
                remaining, k, rounded)
            remaining = rounded
        if remaining > 0:
            # Mid-epoch resume: position the data stream after the restored
            # step so no examples repeat or skip (BackupAndRestore parity).
            batches = (loader.iter_from(int(state.step))
                       if loader is not None and state is not None
                       and int(state.step) > 0
                       else loader)  # None only in service mode (below)
            if service_spec is not None:
                from tensorflow_train_distributed_tpu.data.service import (
                    DataServiceDispatcher,
                )

                if state is not None and int(state.step) > 0:
                    logger.warning(
                        "--data-workers resume: the worker stream "
                        "restarts from epoch 0 (deterministic mid-epoch "
                        "positioning is an in-process loader feature); "
                        "examples may repeat relative to a single "
                        "uninterrupted run")
                dispatcher = DataServiceDispatcher(
                    service_spec,
                    DataConfig(global_batch_size=global_batch,
                               seed=args.seed),
                    num_workers=args.data_workers,
                    host_index=(cluster.process_id
                                if cluster.is_multiprocess else 0),
                    host_count=(cluster.num_processes
                                if cluster.is_multiprocess else 1),
                    ).start()
                service = dispatcher
                batches = iter(dispatcher.client())
            eval_kwargs = {}
            if args.eval_every and args.eval_steps <= 0:
                raise SystemExit(
                    "--eval-every needs --eval-steps N (>0) to size each "
                    "validation run")
            if args.eval_every and args.eval_steps > 0:
                eval_kwargs = dict(
                    eval_batches=make_eval_loader,
                    eval_every=args.eval_every,
                    eval_steps=args.eval_steps,
                )
            state = trainer.fit(
                batches, steps=remaining, state=state,
                steps_per_epoch=(None if loader is None
                                 else loader.steps_per_epoch()),
                **eval_kwargs,
            )
        else:
            logger.info("checkpoint already at/past --steps; nothing to train")

        preempted = watcher is not None and watcher.preempted
        eval_metrics = None
        if args.eval_steps > 0 and not preempted:
            # Skip eval when preempted: the grace window is for the save,
            # and the restarted job re-runs eval at its own end.
            eval_metrics = trainer.evaluate(
                make_eval_loader(), _eval_view(args, state),
                steps=args.eval_steps)
            logger.info("eval: %s", eval_metrics)
        if args.bleu_eval > 0 and not preempted:
            bleu = _bleu_eval(args, task, _eval_view(args, state),
                              make_eval_loader())
            eval_metrics = dict(eval_metrics or {}, bleu=bleu)
            logger.info("BLEU (beam %d, %d batches): %.2f",
                        args.beam_size, args.bleu_eval, bleu)
    finally:
        if service is not None:
            service.stop()
        if watcher is not None:
            watcher.uninstall()
        if ckpt is not None:
            ckpt.close()
    history = next(
        (c.history for c in callbacks if isinstance(c, History)), {})
    return RunResult(state=state, history=history,
                     eval_metrics=eval_metrics, mesh=mesh,
                     preempted=preempted)


def _handle_device_loss(args, dl) -> int:
    """Device-loss exit contract (the elastic half of fault tolerance):
    record the surviving device count in the elastic sidecar — the path
    the supervisor exported (``TTD_ELASTIC_STATE``), falling back to a
    checkpoint-dir sidecar for externally-supervised runs — and hand
    back ``DEVICE_LOSS_EXIT_CODE`` so the supervisor relaunches onto
    the survivors instead of burning the crash budget."""
    import json
    import os
    import time

    from tensorflow_train_distributed_tpu.runtime.supervisor import (
        DEVICE_LOSS_EXIT_CODE, ENV_ELASTIC_STATE,
    )

    path = os.environ.get(ENV_ELASTIC_STATE)
    if not path and args.checkpoint_dir:
        path = os.path.join(args.checkpoint_dir, "elastic.json")
    if path:
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            with open(path, "w") as f:
                json.dump({"survivors": dl.survivors,
                           "time": time.time(),
                           "error": str(dl)[:500]}, f)
        except OSError:
            logger.error("could not write elastic sidecar %s", path,
                         exc_info=True)
    logger.error(
        "DEVICE LOSS: %s — exiting %d (surviving devices: %s; a "
        "supervisor relaunches onto them with the checkpoint "
        "resharded)", dl, DEVICE_LOSS_EXIT_CODE,
        "unknown" if dl.survivors is None else dl.survivors)
    return DEVICE_LOSS_EXIT_CODE


def main(argv: Optional[Sequence[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_configs:
        from tensorflow_train_distributed_tpu.models import registry

        for name in registry.available():
            e = registry.get_entry(name)
            print(f"{name}: dataset={e['dataset']} strategy={e['strategy']} "
                  f"batch={e['global_batch_size']} lr={e['learning_rate']}")
        return 0
    if args.supervise:
        # Re-exec this CLI (minus the supervisor flags) as a supervised
        # child; this process becomes the relaunch loop.
        import sys as _sys

        from tensorflow_train_distributed_tpu.runtime.supervisor import (
            supervise_cli,
        )

        return supervise_cli(
            list(argv) if argv is not None else _sys.argv[1:], args)
    from tensorflow_train_distributed_tpu.runtime.preemption import (
        PREEMPTION_EXIT_CODE,
    )

    try:
        result = run(args)
    except Exception as e:
        # Device-loss classification: an injected DeviceLost
        # (mesh:device_lost fault plan) or a real runtime error whose
        # text matches the known device-failure signatures becomes the
        # device-loss exit contract; every other error crashes as
        # before (the supervisor's crash budget applies).
        from tensorflow_train_distributed_tpu.runtime import faults as _f

        dl = _f.as_device_loss(e)
        if dl is None:
            raise
        return _handle_device_loss(args, dl)
    if result.preempted:
        # The shared exit-code contract (runtime.preemption): non-zero so
        # schedulers reschedule, and distinct so supervisors know this
        # was a coordinated save-and-stop, not a crash (it must not
        # consume the crash restart budget).  143 = SIGTERM'd by
        # convention, which is what happened semantically.
        logger.warning("exiting after preemption-coordinated checkpoint")
        return PREEMPTION_EXIT_CODE
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
