"""Paged KV-cache bookkeeping: block pool + radix prefix index.

The HOST half of the serving engine's paged KV cache
(``serving.ServingEngine`` with ``paged=True``, the default).  Device
memory is one fixed pool of ``[num_blocks, block_size, kv_heads,
head_dim]`` rows per layer (static shape — jit/sharding see one
allocation for the whole session, the Mesh-TensorFlow static-shape
rule; ``kv_cache_int8`` configs store int8 rows with a parallel
``[2, num_blocks, block_size, kv_heads]`` f32 scale pool — same block
ids, half the row bytes, so every table this module hands out covers
both).  The DEVICE read is either an XLA block gather or the fused
paged-attention kernel (``ops.pallas_kernels.paged_attention``) —
both steer their DMA by the tables built here, so this bookkeeping is
layout-authoritative for both legs.  WHICH physical block backs WHICH
logical position of WHICH lane is pure host bookkeeping, and this
module owns all of it:

- ``KVBlockPool``: a free list + per-block reference counts over the
  ``n_blocks`` allocatable physical blocks.  Block id 0 is RESERVED as
  the scratch block (idle/retired lanes' garbage writes land there —
  the paged analog of the linear cache's stale-row rule), so physical
  ids run ``1..n_blocks``.
- ``RadixPrefixIndex``: a radix tree over token ids at BLOCK
  granularity — each edge is one ``block_size``-token chunk, each node
  pins one physical block whose rows hold exactly that chunk's KV.
  Requests sharing a prompt prefix map their leading table entries to
  the same physical blocks (copy-on-write at allocation: suffixes
  always start at a block boundary, so a sharer never writes a shared
  block) and prefill only the suffix.  The tree holds its own pool
  reference per node; lanes add one more while mapped.  Eviction is
  LRU over fully-retired leaves (tree-only references, no children) —
  evicting a leaf may expose its parent, so pressure drains whole
  retired subtrees back to the free list, never a block a live lane
  can still read.

Sharing is exact, not approximate: a node is only ever matched by
token-for-token equality of its chunk, and the KV rows of a shared
block were computed from those very tokens at those very positions
(per-lane positions all start at 0), so a prefix hit reads bit-identical
rows to the prefill it skipped.  Partial (sub-block) prefixes are not
shared — the tail of a prompt that doesn't fill a block is private to
its lane, which is what makes lane writes copy-free.

Everything here is plain Python on the engine's single-threaded host
loop — no jax imports, no device work — so the allocator is testable
without a device and adds nothing to the serving hot path beyond dict
walks over O(prompt/block_size) nodes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# Physical block 0 is the scratch block: never allocated, never shared,
# the write target the engine points idle/retired lanes at.
SCRATCH_BLOCK = 0


class KVBlockPool:
    """Free list + refcounts over ``n_blocks`` allocatable blocks.

    Blocks are freed automatically when their refcount drops to zero;
    ``alloc`` either returns exactly ``n`` ids or None (all-or-nothing,
    so a request that cannot fit is REFUSED admission instead of
    corrupting a live lane with a partial table).
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1:
            raise ValueError(f"need >= 1 allocatable block, got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = n_blocks
        self.block_size = block_size
        # Device bytes one physical block's rows pin across layers
        # (target + draft + int8 scale pools; 0 = unknown).  Set by
        # the engine from the real cache eval_shape, so this host
        # allocator can answer in BYTES — the unit HBM budgets and the
        # /healthz capacity view reason in — not just block counts
        # (``ServingEngine.kv_bytes_in_use`` is the consumer).
        self.bytes_per_block = 0
        # LIFO free list: recently-freed blocks are re-handed first
        # (their rows are most likely still warm in cache hierarchy).
        self._free: List[int] = list(range(n_blocks, 0, -1))
        self._refs: Dict[int, int] = {}
        self.stats = {"allocated_blocks": 0, "freed_blocks": 0}

    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_in_use(self) -> int:
        return self.n_blocks - len(self._free)

    def bytes_in_use(self) -> int:
        """Referenced blocks in device bytes (live lanes + radix
        cache; 0 when the engine never set ``bytes_per_block``)."""
        return self.blocks_in_use() * self.bytes_per_block

    def bytes_total(self) -> int:
        """Allocatable capacity in device bytes."""
        return self.n_blocks * self.bytes_per_block

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks at refcount 1, or None if the free list is
        short (caller may evict from the radix index and retry)."""
        if n < 0:
            raise ValueError(f"alloc takes n >= 0, got {n}")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        self.stats["allocated_blocks"] += n
        return out

    def ref(self, block: int) -> None:
        """One more holder of an already-live block (prefix sharing)."""
        refs = self._refs.get(block, 0)
        if refs <= 0:
            raise ValueError(f"ref of free block {block}")
        self._refs[block] = refs + 1

    def deref(self, block: int) -> None:
        """Drop one holder; the last one out frees the block."""
        refs = self._refs.get(block, 0)
        if refs <= 0:
            raise ValueError(f"deref of free block {block}")
        if refs == 1:
            del self._refs[block]
            self._free.append(block)
            self.stats["freed_blocks"] += 1
        else:
            self._refs[block] = refs - 1


@dataclasses.dataclass
class _RadixNode:
    """One cached block: ``chunk`` (its block_size token ids) keys it
    under ``parent``; ``block`` is the physical id whose rows hold the
    chunk's KV.  The node owns one pool reference for as long as it is
    in the tree."""

    chunk: Tuple[int, ...]
    block: int
    parent: Optional["_RadixNode"]
    children: Dict[Tuple[int, ...], "_RadixNode"] = dataclasses.field(
        default_factory=dict)
    last_used: int = 0


class RadixPrefixIndex:
    """Block-granular radix tree over token ids → physical KV blocks.

    ``match`` walks a prompt chunk by chunk and returns the shared
    leading blocks; ``insert`` registers a lane's freshly-prefilled (or
    decoded) full blocks so LATER requests share them; ``evict_for``
    frees least-recently-used fully-retired leaves under pressure.
    """

    def __init__(self, pool: KVBlockPool):
        self._pool = pool
        self._bs = pool.block_size
        self._root = _RadixNode(chunk=(), block=SCRATCH_BLOCK, parent=None)
        self._clock = 0          # monotonic LRU clock (match/insert bump)
        self._nodes = 0
        self.stats = {"hits": 0, "hit_tokens": 0, "evicted_blocks": 0,
                      "inserted_blocks": 0}

    def __len__(self) -> int:
        return self._nodes

    def cached_blocks(self) -> int:
        return self._nodes

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, tokens, allow_full: bool = False,
              record: bool = True) -> Tuple[int, List[int]]:
        """Longest cached block-aligned prefix STRICTLY shorter than
        ``tokens`` → ``(matched_len, [block_ids])``.  At least one
        suffix token must remain unprefilled (its logit picks the first
        generated token), so at most ``(len-1) // block_size`` blocks
        match — unless ``allow_full`` (preload dedup: no logit is
        needed, the whole span may hit).  Touches matched nodes' LRU
        clocks; takes NO pool references — the caller refs what it
        keeps.  ``record=False`` skips the hit stats (a starved queue
        head re-matches every engine step while it waits; counting each
        retry would report thousands of hits for one admission) —
        recency still refreshes, which keeps the blocks the waiter
        needs at the back of the eviction order."""
        bs = self._bs
        now = self._tick()
        node = self._root
        blocks: List[int] = []
        limit = (len(tokens) - (0 if allow_full else 1)) // bs
        for j in range(limit):
            chunk = tuple(int(t) for t in tokens[j * bs:(j + 1) * bs])
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_used = now
            blocks.append(child.block)
            node = child
        if blocks and record:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += len(blocks) * bs
        return len(blocks) * bs, blocks

    def insert(self, tokens, block_of) -> int:
        """Register the full blocks of ``tokens`` whose rows are valid
        (caller guarantees positions ``[0, n_full*bs)`` hold these
        tokens' KV in the given physical blocks).  ``block_of(j)``
        returns the lane's physical block for table slot ``j``.  Where a
        node already exists the EXISTING block stays canonical (the
        lane's duplicate copy is simply not cached); new nodes take one
        pool reference each.  Returns how many new blocks were cached.
        """
        bs = self._bs
        now = self._tick()
        node = self._root
        added = 0
        for j in range(len(tokens) // bs):
            chunk = tuple(int(t) for t in tokens[j * bs:(j + 1) * bs])
            child = node.children.get(chunk)
            if child is None:
                block = block_of(j)
                if block == SCRATCH_BLOCK:
                    break          # lane has no real block here — stop
                self._pool.ref(block)
                child = _RadixNode(chunk=chunk, block=block, parent=node,
                                   last_used=now)
                node.children[chunk] = child
                self._nodes += 1
                added += 1
            child.last_used = now
            node = child
        self.stats["inserted_blocks"] += added
        return added

    def _evictable(self) -> List[_RadixNode]:
        """Leaves only the tree still references: no live lane can read
        them, no deeper cached block needs them on its path."""
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self._pool.refcount(n.block) == 1:
                out.append(n)
        return out

    def evict_for(self, n_needed: int) -> int:
        """Free least-recently-used retired leaves until ``n_needed``
        blocks are available on the pool's free list (or nothing is
        left to evict).  Evicting a leaf may expose its parent as the
        next candidate, so whole retired subtrees drain under
        sustained pressure.  Returns the number of blocks evicted."""
        evicted = 0
        while self._pool.free_blocks() < n_needed:
            leaves = self._evictable()
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.last_used)
            self._pool.deref(victim.block)
            del victim.parent.children[victim.chunk]
            self._nodes -= 1
            evicted += 1
        self.stats["evicted_blocks"] += evicted
        return evicted

    def check_invariants(self) -> None:
        """Structural audit for tests: every node's block is live in the
        pool (the tree's own reference), node count matches the walk,
        and children are keyed by their own chunk."""
        count = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            count += 1
            assert len(n.chunk) == self._bs, "chunk width != block_size"
            assert self._pool.refcount(n.block) >= 1, "node block is free"
            assert n.block != SCRATCH_BLOCK, "scratch block in the tree"
            for key, child in n.children.items():
                assert key == child.chunk, "child keyed by foreign chunk"
                assert child.parent is n, "broken parent link"
                stack.append(child)
        assert count == self._nodes, "node count drifted"


@dataclasses.dataclass
class LaneKV:
    """One lane's paged-KV claim: the physical block table backing its
    logical positions, split into the ``shared`` leading blocks (radix
    prefix hits — read-only for this lane) and the ``owned`` rest (its
    private, writable blocks).  ``matched`` is the shared token count
    (= len(shared) * block_size)."""

    request_id: int
    matched: int
    shared: List[int]
    owned: List[int]

    def table(self, width: int) -> List[int]:
        """Physical ids for table slots 0..width-1, scratch-padded."""
        row = self.shared + self.owned
        return (row + [SCRATCH_BLOCK] * (width - len(row)))[:width]

    def blocks(self) -> List[int]:
        return self.shared + self.owned
