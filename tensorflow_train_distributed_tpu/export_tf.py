"""Export trained models as TensorFlow SavedModels (jax2tf).

The reference lives in the TF ecosystem: its users serve
``tf.saved_model`` artifacts (TF-Serving / Vertex / TFLite toolchains).
A migration story that ends with "your weights are now jax arrays" leaves
deployment behind — this closes the loop: the task's ``predict_fn``
lowers through ``jax.experimental.jax2tf`` (StableHLO inside a TF graph),
parameters ride as ``tf.Variable``s (checkpointable, not baked-in
constants), and the result loads anywhere TF loads SavedModels.

Scope: inference only (``with_gradient=False``), static input shapes (the
SPMD shape discipline carries over; export per served batch size).
"""

from __future__ import annotations

import jax
import numpy as np


def export_savedmodel(task, params, model_state, sample_batch,
                      out_dir: str, *,
                      batch_polymorphic: bool = True) -> None:
    """Write ``task.predict_fn`` as a TF SavedModel under ``out_dir``.

    ``sample_batch`` fixes the serving signature (names, shapes, dtypes
    of the feature dict).  The exported signature is
    ``serve(**features) -> outputs`` with params stored as variables.

    ``batch_polymorphic``: export with a symbolic leading (batch) dim so
    one artifact serves any batch size; set False if a model's predict
    path can't trace with a dynamic batch (everything else stays static —
    the SPMD shape discipline).
    """
    import tensorflow as tf
    from jax.experimental import jax2tf

    if not hasattr(task, "predict_fn"):
        raise ValueError(
            f"{type(task).__name__} has no predict_fn; nothing to export")
    params_host = jax.tree.map(np.asarray, params)
    model_state_host = jax.tree.map(np.asarray, model_state or {})

    def jfn(p, batch):
        return task.predict_fn(p, model_state_host, batch)

    poly = None
    if batch_polymorphic:
        poly = [None, {k: "(b, ...)" for k in sample_batch}]
    converted = jax2tf.convert(jfn, with_gradient=False,
                               polymorphic_shapes=poly)
    module = tf.Module()
    # Nested python dicts of Variables are tracked by tf.Module, so the
    # checkpoint inside the SavedModel carries real (restorable) weights.
    module.model_params = tf.nest.map_structure(
        lambda x: tf.Variable(x, trainable=False), params_host)
    signature = {
        k: tf.TensorSpec(
            ((None,) + np.shape(v)[1:]) if batch_polymorphic
            else np.shape(v),
            np.asarray(v).dtype, name=k)
        for k, v in sample_batch.items()
    }

    @tf.function(autograph=False, input_signature=[signature])
    def serve(batch):
        return {"output": converted(module.model_params, batch)}

    module.serve = serve
    tf.saved_model.save(
        module, out_dir,
        signatures={"serving_default": serve})


def export_from_registry(config_name: str, checkpoint_dir, out_dir: str,
                         *, platform: str = "cpu") -> None:
    """CLI-oriented wrapper: registry config + orbax checkpoint → SavedModel.

    ``checkpoint_dir=None`` exports a fresh init (signature smoke test).
    """
    from tensorflow_train_distributed_tpu.data.datasets import get_dataset
    from tensorflow_train_distributed_tpu.models import registry
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh, force_platform,
    )
    from tensorflow_train_distributed_tpu.training import Trainer

    if platform:
        force_platform(platform)
    import optax

    entry = registry.get_entry(config_name)
    task = entry["task_factory"]()
    mesh = build_mesh(MeshConfig(data=-1))
    trainer = Trainer(task, optax.sgd(1e-3), mesh)
    source = get_dataset(entry["dataset"],
                         num_examples=2 * entry["global_batch_size"],
                         **entry["dataset_kwargs"])
    from tensorflow_train_distributed_tpu.data import (
        DataConfig, HostDataLoader,
    )

    sample = next(iter(HostDataLoader(
        source, DataConfig(global_batch_size=entry["global_batch_size"]))))
    params = model_state = None
    if checkpoint_dir is not None:
        from tensorflow_train_distributed_tpu.training.checkpoint import (
            CheckpointManager,
        )

        # Inference-state restore: params + model_state (BN running
        # statistics), but NOT the optimizer state — the export must not
        # depend on matching the run's optimizer (adamw vs sgd vs lamb
        # all export alike).
        mgr = CheckpointManager(str(checkpoint_dir), async_save=False)
        restored = mgr.restore_inference_state()
        mgr.close()
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint under {checkpoint_dir}")
        params, model_state = restored
    state = trainer.create_state(sample, params=params)
    # Fresh-init model_state is only correct when the checkpoint carried
    # none (no mutable collections in the model).
    export_savedmodel(task, state.params, model_state or state.model_state,
                      sample, out_dir)
