"""Continuous-batching serving engine (slot-refill decode).

Beyond the reference (a training harness — SURVEY.md §2.1: its SFT
config produces a model users sample from elsewhere): an online serving
loop in the JetStream/Orca style, TPU-first throughout.  ``generate()``
(models/generate.py) serves one static batch: every request waits for
the slowest.  This engine keeps ``slots`` requests in flight over ONE
static-shaped decode program:

- **prefill** runs each arriving prompt alone (batch 1; bucketed
  lengths so a handful of compiles cover every prompt, or
  ``prefill_chunk`` for ONE per-piece program at any prompt length),
  producing that request's per-layer KV rows and first token;
- **insert** copies those rows into a free slot of the big [slots,
  cache_len] cache and pins the slot's per-slot position (the
  ``slot_decode`` cache keeps a VECTOR index — each slot advances from
  its own length; ``layers.MultiHeadAttention._slot_decode_step``);
- **decode chunks** step all slots together ``chunk`` tokens at a time
  (one fetch per chunk, not per token — the tunnel round-trip lesson
  from bench_generate); the host harvests finished requests (EOS or
  budget) between chunks and refills their slots from the queue.

**Async decode pipelining** (PROFILE.md measured the decode step
host-bound: llama_125m 2.13 ms/step vs a 0.38 ms weight-streaming
roofline): by default ``serve_step`` runs with ONE-CHUNK LOOKAHEAD —
the per-slot carry (next token, rng counters) stays device-resident,
chunk N+1 is dispatched from those device arrays the moment chunk N is
in flight (JAX async dispatch: enqueueing needs no sync), and chunk N's
host copy is harvested — stop detection, streaming, refills — while the
device computes N+1.  Stop/refill decisions therefore LAG ONE CHUNK: a
slot whose request finished in chunk N keeps decoding garbage through
chunk N+1; the harvest records which request occupied each slot at
dispatch time and trims anything stale, so outputs are bitwise-identical
to the synchronous path (greedy, seeded sampling, and speculative —
per-slot seed/count streams are deterministic under trimming).
``TTD_NO_OVERLAP=1`` (or ``overlap=False`` / the CLIs' ``--no-overlap``)
is the kill switch back to the synchronous path.

**Decode-priority chunked-prefill scheduling**: admission is NOT
atomic.  A newly admitted request's prefill is a per-slot STAGED
activity (``_PrefillTask``: batch-1 cache under construction + piece
cursor) advanced at most ``prefill_budget`` tokens per ``serve_step``
(default: one piece), enqueued BEHIND the in-flight decode chunk — so
decode chunks for occupied lanes keep flowing every step and a long
prompt can no longer freeze active lanes for its full length.  The
prefill MATH is untouched: the same batch-1 piece programs run in the
same order per request (bucketed, ``prefill_chunk``, prefix-suffix
alike), only their scheduling relative to other lanes' decode changes,
so per-request outputs stay bitwise-identical to atomic admission for
greedy, seeded sampling, and speculative serving (the draft's prefill
stages alongside the target's).  Dense-dispatch MoE keeps its
exact-length single-piece prefill (router capacity is
length-dependent) — one installment regardless of budget — but still
yields to decode between requests.  ``prefill_budget=0`` /
``TTD_NO_INTERLEAVE=1`` (or the CLIs' ``--no-interleave``) is the kill
switch restoring atomic admission byte-for-byte.

**Paged KV cache with cross-request prefix sharing** (the default;
``TTD_NO_PAGED_KV=1`` / ``paged=False`` / the CLIs' ``--no-paged-kv``
restores the per-slot linear cache byte-for-byte): KV rows live in ONE
fixed pool of ``--kv-block-size``-row physical blocks per layer, and
each lane maps its logical positions through a per-lane block table
(``serving_kv`` owns the host bookkeeping: block-pool allocator +
refcounts + a radix tree over token ids at block granularity).  Two
wins over the linear cache:

- **capacity**: a lane holds ``ceil((prompt + max_new) / block_size)``
  blocks instead of a full ``cache_len`` strip, so short requests stop
  reserving long-request memory and admission is keyed on FREE BLOCKS,
  not free slots — a request that cannot get its blocks waits in the
  queue (refused admission, never a corrupted live lane);
- **prefix sharing**: requests whose prompts share a block-aligned
  prefix map their leading table entries to the SAME physical blocks
  (copy-on-write at allocation — a suffix always starts at a block
  boundary, so sharers never write shared blocks) and prefill only the
  suffix.  The radix index is fed automatically at insert/retire, so
  shared system prompts hit warm KV with no ``preload_prefix``
  hand-wiring (which remains supported and now preloads into the same
  pool); retired requests' blocks stay cached until LRU eviction under
  pressure reclaims them.

Prefill itself is UNCHANGED — the same batch-1 linear piece programs
run in the same order (a matched prefix is gathered from the pool into
the batch-1 cache, replacing recompute with a copy), and the decode
grid reads/writes KV through the block table (gather/scatter —
``ops.pallas_kernels.paged_kv_gather`` is the TPU seam), so outputs
stay bitwise-identical to the linear engine for greedy, seeded
sampling, and speculative serving (pinned in
tests/test_serving_paged.py).

Shapes are static everywhere (slot count, cache rows, chunk length,
prompt buckets / prefill pieces, and the paged pool + block tables) —
only cache *contents* and the per-slot index vector change, so XLA
compiles a handful of programs and reuses them for the whole serving
session.

Scope: the decoder families ``generate()`` serves (Llama AND
Mixtral-style MoE — one engine), linear cache, greedy or sampled
decoding (per-request rng streams), with int8 weight-only serving via the same
``quant_scales`` contract as generate and sharded (tensor-parallel)
serving via ``mesh=`` — the models' logical constraints shard weights
and cache over the mesh, GSPMD inserts the collectives, and outputs
stay token-identical.  Shared prompt prefixes prefill once
(``preload_prefix``); later requests prefill only their suffix on a
copied cache.  ``kv_cache_int8`` configs serve here too: the per-slot
prefill cache and the paged pool both quantize with the linear-cache
recipe (int8 rows + per-row f32 scales in a parallel pool), halving
cache HBM so ``--kv-pool-blocks`` can grow effective batch into the
freed headroom.  LoRA-unmerged params and sliding windows keep the
shared-index ``generate()`` path.

**Fused paged attention** (TPU): the paged decode read is ONE Pallas
kernel (``ops.pallas_kernels.paged_attention``) that computes
flash-style attention directly through the block table — the dense
per-lane KV copy ``paged_kv_gather`` would materialize never exists.
``TTD_NO_FUSED_ATTN=1`` (set BEFORE engine construction — the choice
compiles into the decode programs) restores gather-then-attend as the
byte-comparable A/B leg; CPU and sharded (``mesh=``) serving always
use the gather path.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from collections import deque
from functools import partial
from typing import Optional

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from tensorflow_train_distributed_tpu.runtime import compat, events
from tensorflow_train_distributed_tpu.runtime.lint import memcheck
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    compile_site,
    concurrency_guarded,
    dispatch_critical,
    memory_budget,
    thread_role,
)
from tensorflow_train_distributed_tpu import serving_kv
from tensorflow_train_distributed_tpu.models.generate import (
    _decode_model,
    cast_floating,
    filter_logits,
    has_lora_leaves,
    validate_sampling,
)
from tensorflow_train_distributed_tpu.models.quant import (
    check_quant_pairing,
    maybe_quant_variables,
    quantized_inference,
)


@dataclasses.dataclass
class _SlotState:
    request_id: int
    remaining: int                 # generated tokens still allowed
    tokens: list                   # prompt + generated so far
    last_token: int                # feeds the next decode step
    seed: int = 0                  # per-request sampling stream
    count: int = 1                 # tokens sampled so far (rng counter)
    done: bool = False


@dataclasses.dataclass
class _PrefillTask:
    """A request whose prefill is staged across ``serve_step``
    iterations: the slot is RESERVED (no other request can claim it)
    while the batch-1 cache is built piece by piece under the prefill
    budget.  ``cursor``/``d_cursor`` count completed target/draft
    pieces; the caches start ``None`` so staging itself does zero
    device work (pure host bookkeeping)."""

    request_id: int
    prompt: list
    max_new: int
    seed: int
    work: list                     # suffix after any matched prefix
    padded: np.ndarray             # [1, piece * n_pieces] token ids
    piece: int
    n_pieces: int
    resume: int = 0                # rng counter of the first pick
    pre_pair: Optional[tuple] = None   # matched prefix caches (linear)
    cursor: int = 0                # target pieces completed
    cache_1: object = None         # target batch-1 cache in progress
    first: object = None           # device pick after the last piece
    first_host: Optional[int] = None
    d_cursor: int = 0              # draft pieces completed
    d_cache_1: object = None
    kv: object = None              # serving_kv.LaneKV claim (paged mode)
    table: object = None           # np.int32 [n_blk] physical block row


def _overlap_killed() -> bool:
    """The production kill switch: ``TTD_NO_OVERLAP=1`` forces the
    synchronous decode path regardless of how the engine was
    constructed (an env flip needs no redeploy of callers)."""
    return os.environ.get("TTD_NO_OVERLAP", "0") not in ("", "0")


def _interleave_killed() -> bool:
    """``TTD_NO_INTERLEAVE=1`` restores atomic admission (a request's
    whole prefill runs inline on the dispatch path) regardless of the
    engine's ``prefill_budget`` — the same no-redeploy contract as
    ``TTD_NO_OVERLAP``."""
    return os.environ.get("TTD_NO_INTERLEAVE", "0") not in ("", "0")


def _adaptive_spec_killed() -> bool:
    """``TTD_NO_ADAPTIVE_SPEC=1`` pins the draft depth back to the
    fixed ``speculative_k`` bitwise (the controller is never built;
    every round runs the same static-k program a fixed engine runs).
    Read at construction — same no-redeploy contract as
    ``TTD_NO_OVERLAP``."""
    return os.environ.get("TTD_NO_ADAPTIVE_SPEC", "0") not in ("", "0")


def _hbm_autosize_killed() -> bool:
    """``TTD_NO_HBM_AUTOSIZE=1`` makes ``kv_pool_blocks='auto'`` fall
    back to the default heuristic (slots x lanes) with no budget set —
    bitwise the hand-tuned engine's defaults.  Read at construction."""
    return os.environ.get("TTD_NO_HBM_AUTOSIZE", "0") not in ("", "0")


def _device_hbm_bytes() -> Optional[int]:
    """Per-device memory capacity in bytes, for the autosize solve:
    ``TTD_HBM_BYTES=<bytes>`` overrides (tests and CPU hosts, where
    jax reports no limit); otherwise the first local device's
    ``memory_stats()['bytes_limit']`` (TPU/GPU backends report it;
    CPU typically returns None → the caller refuses with a clear
    error instead of guessing)."""
    env = os.environ.get("TTD_HBM_BYTES", "")
    if env not in ("", "0"):
        return int(env)
    dev = jax.local_devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if not stats:
        return None
    return int(stats.get("bytes_limit", 0) or 0) or None


def _paged_killed() -> bool:
    """``TTD_NO_PAGED_KV=1`` restores the per-slot LINEAR cache
    byte-for-byte (contiguous ``cache_len`` rows per lane, manual
    ``preload_prefix`` prefix caching) regardless of how the engine was
    constructed — the same no-redeploy contract as ``TTD_NO_OVERLAP``."""
    return os.environ.get("TTD_NO_PAGED_KV", "0") not in ("", "0")


def _bucket_len(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest prefill "
                     f"bucket {buckets[-1]}")


@concurrency_guarded
class ServingEngine:
    """Continuous-batching decoder over a fixed slot grid.

    ``submit()`` requests, then ``run()`` to completion.  Greedy by
    default — output token-identical to ``generate(config, params,
    prompt, max_new)`` greedy (pinned by tests/test_serving.py); with
    ``temperature``/``top_k``/``top_p`` set, each request samples from
    its OWN rng stream (seeded at submit), so sampled outputs are
    reproducible and independent of slot placement.  Either way slots
    only change *when* work happens, never the math: per-slot positions
    give every request the same RoPE/mask view it would have alone.
    """

    # The engine is single-threaded (the driver loop owns every
    # mutating call) EXCEPT these cross-thread surfaces.  The prefix
    # stores: handler threads validate while the driver LRU-touches —
    # every access locks (the PR 6 review-pass bug, now enforced).
    # The stats dicts: single-writer on the driver/offline loop (which
    # reads its own writes lock-free — the owner-role exemption), but
    # scrape-thread readers (`/metrics` FnCounters and gauges sampling
    # ``kv_prefix_hit_tokens``/``overlap_ratio``/... at scrape time)
    # take ``_stats_lock``, and every WRITE takes it too so a scrape
    # between the fields of one logical update (hits vs hit_tokens;
    # harvest_s vs overlapped_harvest_s) can no longer observe a torn
    # pair.
    _GUARDED_BY = {
        "_prefix_caches": ("_prefix_lock",),
        "_preloaded": ("_prefix_lock",),
        "kv_stats": ("_stats_lock", "driver", "main"),
        "prefill_stats": ("_stats_lock", "driver", "main"),
        "overlap_stats": ("_stats_lock", "driver", "main"),
        "spec_stats": ("_stats_lock", "driver", "main"),
        "_spec_ctrl": ("_stats_lock", "driver", "main"),
    }

    def __init__(self, config, params, *, slots: int = 8,
                 cache_len: Optional[int] = None, eos_id: Optional[int] = None,
                 chunk: int = 8, cast_params: bool = True,
                 quant_scales=None, mesh=None, rules=None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 prefill_chunk: Optional[int] = None,
                 draft_config=None, draft_params=None,
                 draft_quant_scales=None,
                 speculative_k: int = 0,
                 spec_depths=None,
                 prompt_buckets=(32, 64, 128, 256, 512, 1024),
                 overlap: Optional[bool] = None,
                 prefill_budget: Optional[int] = None,
                 paged: Optional[bool] = None,
                 kv_block_size: int = 16,
                 kv_pool_blocks=None,
                 prefix_cache_limit: int = 32,
                 hbm_budget_bytes: Optional[int] = None,
                 hbm_headroom: float = 0.1):
        # MoeConfig has no window knob; getattr keeps one check covering
        # both decoder families.  kv_cache_int8 configs SERVE here (the
        # per-slot and paged caches both quantize with the linear-cache
        # recipe); only the rolling-window/sink cache shapes stay
        # generate()-only.
        if (getattr(config, "sliding_window", None) is not None
                or getattr(config, "attention_sinks", 0)):
            raise ValueError(
                "the serving engine's per-slot caches hold the full "
                "context; sliding_window / attention_sinks configs "
                "serve through models.generate (kv_cache_int8 is "
                "supported here)")
        if has_lora_leaves(params):
            raise ValueError(
                "merge LoRA adapters before engine serving: params = "
                "models.lora.merge_lora(params, spec)")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        # Sampling config is engine-level (a deployment knob, static in
        # the compiled programs); the rng stream is PER REQUEST (seeded
        # at submit) so outputs are reproducible regardless of slot
        # placement or chunk boundaries.  One shared validator with
        # generate().
        validate_sampling(temperature, top_k, top_p)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self._greedy = temperature == 0.0
        self.config = config
        self.slots = slots
        self.cache_len = cache_len or config.max_positions
        if self.cache_len > config.max_positions:
            raise ValueError(
                f"cache_len {self.cache_len} exceeds max_positions "
                f"{config.max_positions}")
        self.eos_id = eos_id
        self.chunk = chunk
        # HBM budget (memcheck, the third lint vertical): the byte
        # ceiling this engine's declared pools — grid KV pools, staged
        # prefill caches, stored prefix pairs — are held to.  None =
        # track-only: the ``TTD_MEMCHECK=1`` sanitizer still ledgers
        # every pool (the ttd_engine_hbm_bytes{pool=...} gauge feed)
        # but never raises; with a budget set, the allocation that
        # would exceed it raises MemoryBudgetError with the live set
        # diffed, and validate_request refuses admissions whose
        # projected bytes cannot fit (alongside the free-blocks
        # check).
        if hbm_budget_bytes is not None and hbm_budget_bytes < 1:
            raise ValueError(
                f"hbm_budget_bytes must be >= 1, got {hbm_budget_bytes}")
        self.hbm_budget_bytes = hbm_budget_bytes
        self._prefill_bytes_memo: Optional[int] = None
        # Dense-dispatch MoE prefill must run at the EXACT prompt
        # length: the router's per-group capacity is ⌈cf·k·S/E⌉ — a
        # bucket-padded S changes the capacity constant, so drop
        # behavior (and therefore tokens) would diverge from
        # generate()'s unpadded prefill.  Exact lengths cost one prefill
        # compile per distinct length instead of per bucket (and the
        # buckets are never consulted) — the engine warns per new
        # length.  dispatch="gmm" (dropless) routes every token
        # independently with no capacity competition, so pad tokens
        # cannot perturb real ones — bucketed AND chunked prefill stay
        # exact there (parity-pinned in tests/test_serving.py).
        from tensorflow_train_distributed_tpu.models.moe import MoeConfig

        self._exact_prefill = (isinstance(config, MoeConfig)
                               and config.dispatch != "gmm")
        # Chunked prefill: long prompts run through the SAME per-piece
        # program in ``prefill_chunk``-token pieces (the decode cache
        # appends multi-token blocks at any position), bounding prefill
        # memory/compile variety to one chunk shape.  MoE must prefill
        # whole (per-chunk routing capacity would diverge from
        # generate()'s full-prompt prefill — the exact-length rule).
        if prefill_chunk is not None:
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            if self._exact_prefill:
                raise ValueError(
                    "prefill_chunk is unsupported for dense-dispatch "
                    "MoE configs: the router's per-group capacity "
                    "depends on the prefill length, so chunking would "
                    "change routing vs generate() (dense MoE prefills "
                    "at the exact length; dispatch='gmm' is dropless "
                    "and supports chunked/bucketed prefill)")
        self.prefill_chunk = prefill_chunk
        self.prompt_buckets = tuple(
            b for b in sorted(prompt_buckets) if b <= self.cache_len)
        if (not self.prompt_buckets and not self._exact_prefill
                and prefill_chunk is None):
            raise ValueError("no prompt bucket fits cache_len")
        # int8 weight-only serving: same pairing contract as generate()
        # (one shared check), and every Dense runs the fused dequant
        # path via the (free when inactive) quantized_inference
        # interceptor.
        check_quant_pairing(params, quant_scales)
        if cast_params:
            params = cast_floating(params, config.dtype)
        self._variables = maybe_quant_variables(params, quant_scales)
        # Paged KV cache (the default; ``paged=False`` or
        # TTD_NO_PAGED_KV=1 restores the linear per-slot cache
        # byte-for-byte).  The pool is sized in BLOCKS: by default
        # slots * ceil(cache_len / block_size) — the linear cache's
        # exact memory, so defaults change layout, never capacity;
        # operators shrink/grow it with ``kv_pool_blocks`` (admission
        # then keys on free blocks, not free slots).
        if kv_block_size < 1:
            raise ValueError(
                f"kv_block_size must be >= 1, got {kv_block_size}")
        self.kv_block_size = int(kv_block_size)
        self._kv_nblk_lane = -(-self.cache_len // self.kv_block_size)
        self.paged = ((True if paged is None else bool(paged))
                      and not _paged_killed())
        # ``kv_pool_blocks="auto"``: solve the pool size + HBM budget
        # exactly from the device's reported memory and the memcheck
        # projection (pool rows + batch-1 prefill transients + draft
        # pools + ``hbm_headroom``) — one binary lands correctly sized
        # on any chip.  The solve itself is DEFERRED below the draft
        # section: it eval_shapes BOTH models' caches, so both
        # variable trees must exist first.  ``TTD_NO_HBM_AUTOSIZE=1``
        # (or a linear-cache engine, which has no pool) falls back to
        # the default heuristic with no budget set — bitwise the
        # hand-tuned defaults.
        if not 0.0 <= hbm_headroom < 1.0:
            raise ValueError(
                f"hbm_headroom must be in [0, 1), got {hbm_headroom}")
        self._hbm_headroom = float(hbm_headroom)
        self._hbm_autosized = 0
        autosize = kv_pool_blocks == "auto"
        if autosize:
            if hbm_budget_bytes is not None:
                raise ValueError(
                    "kv_pool_blocks='auto' solves hbm_budget_bytes "
                    "itself; pass one or the other")
            if _hbm_autosize_killed() or not self.paged:
                autosize = False
                kv_pool_blocks = None
        elif isinstance(kv_pool_blocks, str):
            raise ValueError(
                f"kv_pool_blocks must be an int or 'auto', got "
                f"{kv_pool_blocks!r}")
        if not autosize:
            if kv_pool_blocks is None:
                kv_pool_blocks = slots * self._kv_nblk_lane
            if kv_pool_blocks < 1:
                raise ValueError(
                    f"kv_pool_blocks must be >= 1, got {kv_pool_blocks}")
        # kv_stats counts ENGINE-visible cache economics (the /metrics
        # feed): tokens of prefill skipped via radix prefix hits,
        # blocks LRU-evicted under allocation pressure, and admissions
        # refused for want of blocks.
        self.kv_stats = {"prefix_hit_tokens": 0, "prefix_hits": 0,
                         "evictions": 0, "alloc_refusals": 0}
        # Prefill always runs batch-1 on the LINEAR cache (the same
        # piece programs as the linear engine — prefix reuse replaces
        # recompute with a pool gather, never changes the math); only
        # the slot-grid decode/verify/insert programs go paged.
        self._prefill_model = _decode_model(config, self.cache_len,
                                            slot_decode=True)
        # (the slot-grid decode model is built below, once
        # kv_pool_blocks has resolved — possibly via the autosize
        # solve, which needs the draft variables prepared first)
        # Speculative decoding across ALL slots: each round the draft
        # proposes k tokens per slot, the target verifies the k+1 block
        # in one call, and each slot accepts its own prefix — the
        # per-slot cache index makes the rollback a per-slot index
        # decrement (the library path, models/speculative.py, is batch-1
        # precisely because the shared-index cache cannot do this).
        self._spec_k = int(speculative_k)
        self._draft_model = None
        if (draft_config is None) != (draft_params is None):
            raise ValueError("draft_config and draft_params come together")
        if draft_quant_scales is not None and draft_config is None:
            raise ValueError("draft_quant_scales needs draft_config/params")
        if self._spec_k and draft_config is None:
            raise ValueError("speculative_k needs draft_config/params")
        if draft_config is not None:
            if self._spec_k < 1:
                raise ValueError(
                    f"draft_config needs speculative_k >= 1, got "
                    f"{self._spec_k}")
            if getattr(draft_config, "attention_sinks", 0):
                # Same screen as the target's: a bad draft config would
                # otherwise crash inside run(), aborting in-flight work.
                # (kv_cache_int8 drafts serve — same caches as the
                # target's.)
                raise ValueError(
                    "the draft uses the per-slot caches too; "
                    "attention_sinks draft configs are unsupported")
            from tensorflow_train_distributed_tpu.models.speculative import (
                _reject_config,
            )

            _reject_config("target", config)
            _reject_config("draft", draft_config)
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_config.vocab_size} != target "
                    f"vocab {config.vocab_size}")
            if has_lora_leaves(draft_params):
                raise ValueError("merge the draft's LoRA adapters first")
            # int8 weight-only serving composes with speculation (the
            # production pairing: decode is weight-HBM-bound on BOTH
            # models) — each tree carries its own scales, same pairing
            # contract as the target's.  Acceptance is defined against
            # the quantized target's own distribution, so greedy stays
            # token-identical to int8 generate() and sampled keeps the
            # int8 target's law.
            check_quant_pairing(draft_params, draft_quant_scales)
            if cast_params:
                draft_params = cast_floating(draft_params,
                                             draft_config.dtype)
            self._draft_variables = maybe_quant_variables(
                draft_params, draft_quant_scales)
            # The draft shares the TARGET's block tables (its lanes'
            # logical layouts are identical — both caches hold the same
            # row sets by the speculative invariant), so one allocation
            # covers both pools; only the pool row shapes differ.
            self._draft_prefill_model = _decode_model(
                draft_config, self.cache_len, slot_decode=True)
        # Acceptance-adaptive speculation (opt-in): precompiled
        # draft-depth buckets + a host-side controller that SELECTS
        # among them per round from measured acceptance — it never
        # changes any program's math (forced-depth parity pinned in
        # tests/test_spec_adaptive.py).  ``TTD_NO_ADAPTIVE_SPEC=1``
        # pins the fixed ``speculative_k`` program bitwise.
        self._spec_ctrl = None
        if spec_depths is not None:
            if draft_config is None:
                raise ValueError("spec_depths needs draft_config/params")
            if not _adaptive_spec_killed():
                from tensorflow_train_distributed_tpu.models.speculative import (  # noqa: E501
                    DepthController,
                )

                self._spec_ctrl = DepthController(spec_depths)
        # ── deferred pool sizing + slot-grid decode models ──
        if autosize:
            kv_pool_blocks, budget = self._solve_hbm_autosize(
                config, draft_config)
            self.hbm_budget_bytes = budget
            self._hbm_autosized = budget
        self._kv_pool = self._radix = None
        if self.paged:
            self._kv_pool = serving_kv.KVBlockPool(
                kv_pool_blocks, self.kv_block_size)
            self._radix = serving_kv.RadixPrefixIndex(self._kv_pool)
        self._model = (_decode_model(
            config, self.cache_len, slot_decode=True,
            paged_kv_blocks=1 + kv_pool_blocks,
            kv_block_size=self.kv_block_size)
            if self.paged else self._prefill_model)
        if draft_config is not None:
            self._draft_model = (_decode_model(
                draft_config, self.cache_len, slot_decode=True,
                paged_kv_blocks=1 + kv_pool_blocks,
                kv_block_size=self.kv_block_size)
                if self.paged else self._draft_prefill_model)
        # Sharded serving: with a mesh, every device call runs under
        # jax.set_mesh + the logical-axis rules, so the models' logical
        # constraints shard weights/cache/activations (e.g. heads over
        # ``tensor``) exactly as in training — GSPMD inserts the
        # collectives; the engine's host logic is unchanged.  ``rules``
        # mirrors Trainer(..., rules=): pass the training-time rules so
        # serving shards the way the model trained (None = defaults).
        self._mesh = mesh
        self._rules = rules
        self._queue: deque = deque()
        self._outputs: dict = {}
        self._next_id = 0
        self._slot_states: list[Optional[_SlotState]] = [None] * slots
        self._cache = None  # built lazily on first insert (needs params)
        self._d_cache = None               # draft slots (speculative)
        # "rounds" counts ENGINE rounds (one _spec_round call);
        # "slot_rounds" counts active slots across them — the
        # denominator for acceptance rates (accepted/(slot_rounds·k)).
        self.spec_stats = {"rounds": 0, "slot_rounds": 0,
                           "drafted": 0, "drafted_accepted": 0,
                           "emitted": 0}
        self._cache_shapes: dict = {}  # (draft, batch, grid) -> eval_shape
        self._moe_prefill_lens: set = set()  # distinct exact-prefill lens
        # Linear-path prefix caches (paged mode subsumes them via the
        # radix index): LRU-BOUNDED — keyed by tuple(tokens), these
        # hold device memory, and an unbounded dict leaks under many
        # distinct preloaded prefixes.  ``prefix_cache_limit`` caps the
        # entries; preload past it evicts the least recently matched.
        if prefix_cache_limit < 1:
            raise ValueError(f"prefix_cache_limit must be >= 1, got "
                             f"{prefix_cache_limit}")
        self.prefix_cache_limit = prefix_cache_limit
        from collections import OrderedDict
        self._prefix_caches: OrderedDict = OrderedDict()
        # The ONE engine structure gateway handler threads READ while
        # the driver thread writes: validate_request scans the prefix
        # stores concurrently with admission's LRU touches / preload's
        # eviction, and an OrderedDict mutated mid-iteration raises in
        # the READER.  Everything touching _prefix_caches/_preloaded
        # holds this lock (admission's hold is nanoseconds — dict
        # walks, never device work).
        import threading
        self._prefix_lock = threading.Lock()
        # Guards the stats dicts' cross-thread consistency: writes on
        # the driver loop are per-admission/per-step (never per-token),
        # scrape-thread readers (`/metrics` callables) take it so a
        # multi-field update is observed whole.  Declared in
        # ``_GUARDED_BY`` above; ttd-lint enforces the discipline.
        self._stats_lock = threading.Lock()
        # Paged-mode per-lane claims and admission bookkeeping:
        # _lane_kv[slot] holds the LaneKV while the lane decodes;
        # _stale_slots are lanes retired/cancelled since the last
        # dispatch — their block-table rows must be zeroed (pointed at
        # the scratch block) BEFORE the next decode program runs, or
        # the overlap scheduler's one garbage chunk would write into
        # blocks already freed to (and maybe reallocated by) someone
        # else.  _preloaded records preload_prefix token tuples for
        # validate_request's bucket rule (the radix itself is
        # evictable, so validation must not depend on it).
        self._lane_kv: list = [None] * slots
        self._stale_slots: set = set()
        self._preloaded: dict = {}
        self._kv_refused_rid: Optional[int] = None  # dedup refusal count
        # Async decode pipelining (one-chunk lookahead).  ``overlap``
        # None/True enables it; TTD_NO_OVERLAP=1 kills it either way.
        self.overlap = ((True if overlap is None else bool(overlap))
                        and not _overlap_killed())
        # Decode-priority chunked-prefill scheduling: prefill_budget
        # tokens of staged prefill advance per serve_step (None = one
        # piece — the default installment); 0 (or TTD_NO_INTERLEAVE=1)
        # is the kill switch back to atomic admission.
        if prefill_budget is not None and prefill_budget < 0:
            raise ValueError(
                f"prefill_budget must be >= 0 (0 = atomic admission), "
                f"got {prefill_budget}")
        self.prefill_budget = prefill_budget
        self.interleave = (prefill_budget != 0
                           and not _interleave_killed())
        self._staging: dict = {}       # slot -> _PrefillTask (FIFO)
        # stall_s: wall time spent prefilling while >= 1 lane was
        # decoding with NO successor decode chunk in flight to hide it
        # (the head-of-line blocking this scheduler removes — the
        # gateway exposes it as ttd_engine_prefill_stall_seconds);
        # installments: budget installments run; staged_requests:
        # requests that went through the staged path.
        self.prefill_stats = {"installments": 0, "staged_requests": 0,
                              "stall_s": 0.0}
        # The chunk in flight: rids pins which request occupied each
        # slot AT DISPATCH — harvest trims anything that retired or was
        # refilled since (the one-chunk decision lag made safe).
        self._inflight: Optional[dict] = None
        # Device-resident carry feeding the NEXT dispatch: (tok [slots],
        # counts [slots]) — never materialized on the host, so a chunk
        # can be enqueued while its predecessor still computes.
        self._carry = None
        self._refills: set = set()     # slots refilled since last dispatch
        # overlapped_harvests counts harvest passes that ran with a
        # successor chunk already in flight; the _s pair feeds
        # overlap_ratio() (the host-stall share the lookahead hides).
        self.overlap_stats = {"chunks": 0, "overlapped_harvests": 0,
                              "harvest_s": 0.0,
                              "overlapped_harvest_s": 0.0}
        # Fused paged attention (ops.pallas_kernels.paged_attention):
        # decided at construction from the same env/backend rule the
        # decode trace reads (TTD_NO_FUSED_ATTN kills it; TPU default)
        # — recorded here so dispatch spans and benches can tag which
        # leg ran.  Flip the switch BEFORE constructing the engine:
        # the decision burns into the compiled decode programs.
        from tensorflow_train_distributed_tpu.ops import (
            pallas_kernels as _pk,
        )

        self.kv_cache_int8 = bool(getattr(config, "kv_cache_int8",
                                          False))
        # Same mesh rule as layers._fused_paged_ok: any >1-way mesh
        # keeps the XLA gather (GSPMD partitions it); a trivial mesh
        # does not veto the kernel.
        meshed = (self._mesh is not None
                  and any(v > 1 for v in self._mesh.shape.values()))
        self._fused_attn = bool(self.paged and not meshed
                                and _pk.use_fused_paged_attention())
        # Span-arg form, precomputed: the dispatch-critical window must
        # not run int() (the dispatch lint cannot tell a host bool from
        # a device scalar there, and keeping the window conversion-free
        # is the cheaper discipline anyway).
        self._fused_tag = 1 if self._fused_attn else 0
        # Device bytes the paged pools pin (target + draft, int8 scale
        # pools included) — computed once from the memoized cache
        # eval_shape (host-only trace, no device work) so the /metrics
        # scrape thread reads a plain int.  The --kv-pool-blocks
        # oversizing lever is sized against this number.
        self._kv_pool_bytes = 0
        if self.paged:
            def _pool_bytes(struct):
                return sum(
                    int(np.prod(leaf.shape))
                    * jnp.dtype(leaf.dtype).itemsize
                    for p, leaf in
                    jax.tree_util.tree_flatten_with_path(struct)[0]
                    if getattr(p[-1], "key", "") in
                    ("key_pool", "value_pool", "kv_pool_scales"))

            self._kv_pool_bytes = _pool_bytes(
                self._cache_struct(self.slots, grid=True))
            if self._draft_model is not None:
                self._kv_pool_bytes += _pool_bytes(
                    self._cache_struct(self.slots, draft=True,
                                       grid=True))
            # Per-block row bytes across layers (draft + int8 scale
            # pools included): the host allocator's byte view of its
            # own blocks, so block-count accounting (serving_kv) can
            # be read in BYTES too — what admission and the memcheck
            # gauges reason in.
            self._kv_pool.bytes_per_block = (
                self._kv_pool_bytes // (1 + self._kv_pool.n_blocks))
        if self.hbm_budget_bytes is not None:
            # Budgeted engines precompute the admission projection NOW:
            # validate_request runs on gateway HANDLER threads, which
            # must read a memoized int, never trace an eval_shape
            # concurrently with the driver.
            self._prefill_pair_bytes()

    def _ctx(self):
        """Mesh + logical-rules context for device calls (no-op unsharded).

        ``jax.set_mesh`` must wrap the jitted CALL, not sit inside the
        traced function (trainer.py:432 lesson)."""
        if self._mesh is None:
            return contextlib.nullcontext()
        from tensorflow_train_distributed_tpu.parallel import (
            sharding as sharding_lib,
        )

        stack = contextlib.ExitStack()
        stack.enter_context(sharding_lib.with_logical_rules(
            self._mesh, *(() if self._rules is None else (self._rules,))))
        stack.enter_context(compat.set_mesh(self._mesh))
        return stack

    # -- jitted programs ---------------------------------------------------

    def _pick(self, logits, seeds, counts):
        """Next token per slot from [slots, V] logits.

        Greedy: argmax.  Sampling: each slot draws from ITS OWN stream
        — key = fold_in(key(seed), tokens_drawn_so_far) — so a
        request's tokens do not depend on slot placement, neighbors, or
        chunk boundaries (reproducible under any contention).
        """
        logits = logits.astype(jnp.float32)
        if self._greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = filter_logits(logits, temperature=self.temperature,
                               top_k=self.top_k, top_p=self.top_p)
        keys = jax.vmap(jax.random.fold_in)(
            jax.vmap(jax.random.key)(seeds.astype(jnp.uint32)), counts)
        return jax.vmap(
            lambda k, l: jax.random.categorical(k, l)
        )(keys, logits).astype(jnp.int32)

    # Compile discipline (ttd-lint compilecheck + TTD_COMPILECHECK=1):
    # every program below declares which bucket rule pads its dynamic
    # dims, which args it donates, and how many distinct signatures one
    # engine may legitimately compile.  Prefill pieces see one shape
    # per prompt bucket (or ONE prefill_chunk shape) — except
    # dense-MoE exact-length prefill, which deliberately compiles per
    # distinct prompt length (the engine warns per new length), hence
    # the wider budget.  The grid programs (decode/spec/insert/reset)
    # are shape-fixed per engine: tiny budgets, so an un-bucketed
    # shape reaching them raises on the FIRST excess dispatch.
    @compile_site(buckets="prompt_buckets|prefill_chunk|exact(dense-MoE)",
                  donates=(2,), statics=(0,), max_compiles=32)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _prefill_piece(self, variables, cache, tokens_1xl, local_idx,
                       seed, count0):
        """One batch-1 prefill piece appended to ``cache`` (a zeroed
        cache == fresh, so the whole-prompt case is a single piece).

        Pad rows in the final piece are harmless: causal masking keeps
        them invisible to the real rows (they sit AFTER every real
        position), the first token reads the logit at ``local_idx``
        (the last REAL row of this piece), and insert() pins the slot's
        index to the true prompt length so decode overwrites each pad
        row before any query can attend it (writes precede reads at
        every position).

        ``count0`` is the rng counter of the pick — 0 for a fresh
        request; a resumed request (failover re-admission whose prompt
        tail is its own earlier output) picks at its original stream
        position, so the continuation is the one the uninterrupted run
        would have sampled.
        """
        with quantized_inference():
            logits, vs = self._prefill_model.apply(
                dict(variables, cache=cache), tokens_1xl,
                mutable=["cache"])
        first = self._pick(logits[:, local_idx],
                           seed[None], count0[None])[0]
        return vs["cache"], first.astype(tokens_1xl.dtype)

    @compile_site(buckets="prompt_buckets|prefill_chunk",
                  donates=(2,), statics=(0,), max_compiles=32)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _draft_prefill_piece(self, variables, cache, tokens_1xl):
        """Draft-model prefill piece (no token pick — the draft only
        needs its KV rows; pad rows are harmless by the same
        write-before-read rule as the target's)."""
        with quantized_inference():
            _, vs = self._draft_prefill_model.apply(
                dict(variables, cache=cache), tokens_1xl,
                mutable=["cache"])
        return vs["cache"]

    def _accept_block_sampled(self, d_block, q, logits, round_keys,
                              dtype, k):
        """Engine face of the shared rejection rule
        (``models.speculative.sampled_accept``): filter/softmax the
        target's raw ``logits`` [B, k+1, V] with the engine's sampling
        knobs and derive the per-slot acceptance uniforms (draw index
        k+1) and residual/bonus keys (k+2) from ``round_keys``.  ``k``
        is the ROUND's draft depth (a static under `_spec_round`'s
        trace) — under adaptive speculation different rounds run
        different depths, so the depth can no longer be read off
        ``self``."""
        from tensorflow_train_distributed_tpu.models.speculative import (
            sampled_accept,
        )

        p = jax.nn.softmax(filter_logits(
            logits, temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p), axis=-1)            # [B, k+1, V]
        us = jax.vmap(lambda kk: jax.random.uniform(
            jax.random.fold_in(kk, k + 1), (k,)))(round_keys)
        final_keys = jax.vmap(
            lambda kk: jax.random.fold_in(kk, k + 2))(round_keys)
        emit, emitted, a, final = sampled_accept(
            d_block, q, p, us, final_keys)
        return (emit.astype(dtype), emitted, a, final.astype(dtype))

    @compile_site(buckets="spec-depth buckets (one program per k)",
                  donates=(3, 4), statics=(0, 8), max_compiles=8)
    @partial(jax.jit, static_argnums=(0, 8), donate_argnums=(3, 4))
    def _spec_round(self, t_vars, d_vars, t_cache, d_cache, tok, seeds,
                    counts, k):
        """One speculative round for ALL slots: the draft proposes k
        tokens per slot (k+1 steps — the last append-only so both
        caches hold identical row sets), the target verifies each
        slot's k+1 block in one call, each slot accepts its own
        longest matching prefix, and both cache indices rewind
        PER SLOT by k+1-emitted (rows beyond stay stale-but-invisible:
        masks are position-based and writes precede reads).

        ``k`` is STATIC: each draft depth compiles its own program, so
        the adaptive controller picks among a fixed bucket set
        (``spec_depths``) without retracing — a fixed-depth engine only
        ever calls one signature.  Depth 0 degenerates to plain decode
        (one append-only draft step keeps the draft cache's row set in
        lockstep for later deepening; the empty d_block accepts
        trivially and the round emits exactly the target's own pick) —
        greedy depth-0 rounds are token-identical to `_decode_chunk`
        steps.

        Returns (t_cache, d_cache, emit [B, k+1], emitted [B],
        next_tok [B], accepted [B]).  Greedy: emitted tokens are
        exactly the target's greedy choices — token-identical to
        non-speculative serving (pinned in tests).  Sampled: draft
        proposals are accepted by the rejection rule
        (``_accept_block_sampled``), so outputs are distributed as
        plain sampled serving — same law, fewer target steps; the
        per-slot stream (``seeds``/``counts``) keys every draw, so a
        round is reproducible independent of slot placement.
        """
        round_keys = jax.vmap(jax.random.fold_in)(
            jax.vmap(jax.random.key)(seeds.astype(jnp.uint32)), counts)

        def draft_step(c, j):
            cache, tk = c
            with quantized_inference():
                logits, upd = self._draft_model.apply(
                    dict(d_vars, cache=cache), tk[:, None],
                    mutable=["cache"])
            logits = logits[:, -1].astype(jnp.float32)
            if self._greedy:
                nxt = jnp.argmax(logits, -1).astype(tk.dtype)
                return (upd["cache"], nxt), nxt
            filt = filter_logits(logits, temperature=self.temperature,
                                 top_k=self.top_k, top_p=self.top_p)
            keys = jax.vmap(lambda kk: jax.random.fold_in(kk, j))(
                round_keys)
            nxt = jax.vmap(jax.random.categorical)(keys, filt).astype(
                tk.dtype)
            return (upd["cache"], nxt), (nxt, jax.nn.softmax(filt, -1))

        (d_cache, _), scanned = jax.lax.scan(
            draft_step, (d_cache, tok), jnp.arange(k + 1))
        drafts = scanned if self._greedy else scanned[0]
        drafts = jnp.moveaxis(drafts, 0, 1)        # [B, k+1]; d0..dk
        d_block = drafts[:, :k]                    # [B, k]

        block = jnp.concatenate([tok[:, None], d_block], axis=1)
        with quantized_inference():
            logits, upd = self._model.apply(
                dict(t_vars, cache=t_cache), block, mutable=["cache"])
        t_cache = upd["cache"]
        logits = logits.astype(jnp.float32)        # [B, k+1, V]

        if self._greedy:
            # Per slot: emit the longest matching prefix then the
            # target's own pick (one shared rule with the batch-1
            # library path).
            from tensorflow_train_distributed_tpu.models.speculative import (
                accept_block,
            )

            preds = jnp.argmax(logits, -1).astype(tok.dtype)
            emit, emitted, a, next_tok = accept_block(d_block, preds)
        else:
            q = jnp.moveaxis(scanned[1], 0, 1)[:, :k]   # [B, k, V]
            emit, emitted, a, next_tok = self._accept_block_sampled(
                d_block, q, logits, round_keys, tok.dtype, k)

        # Per-slot rewind: both caches advanced k+1 this round; the
        # accepted context is old + emitted, i.e. index -= k+1-emitted.
        back = (k + 1) - emitted                   # [B]

        def rewind(path, leaf):
            if any(getattr(p, "key", "") == "index" for p in path):
                return leaf - back.astype(leaf.dtype)
            return leaf

        t_cache = jax.tree_util.tree_map_with_path(rewind, t_cache)
        d_cache = jax.tree_util.tree_map_with_path(rewind, d_cache)
        # counts + emitted: the NEXT round's rng counters, computed in
        # the same program so the overlap scheduler's device-resident
        # carry costs zero extra dispatches (the sync path ignores it).
        return (t_cache, d_cache, emit, emitted, next_tok, a,
                counts + emitted)

    @compile_site(buckets="slot-grid (shape-fixed per engine)",
                  donates=(1,), statics=(0,), max_compiles=4)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _insert(self, cache_b, cache_1, slot, true_len):
        """Copy a prefilled request's cache rows into ``slot`` and pin
        the slot's per-slot index to the TRUE prompt length.  Leaves are
        [..., B, C, kv_heads, head_dim] (a leading layer axis under
        scan_layers), the index [..., B], and — int8 configs — the
        kv_scales [..., 2, B, C, kv_heads] (batch axis at ndim-3, not
        ndim-4)."""
        def ins(path, pb, p1):
            name = getattr(path[-1], "key", "")
            if name == "index":
                return pb.at[..., slot].set(true_len)
            return jax.lax.dynamic_update_slice_in_dim(
                pb, p1, slot,
                axis=pb.ndim - (3 if name == "kv_scales" else 4))

        return jax.tree_util.tree_map_with_path(ins, cache_b, cache_1)

    # -- paged-pool programs -----------------------------------------------

    @staticmethod
    def _path_key(path) -> tuple:
        return tuple(getattr(k, "key", str(k)) for k in path)

    def _lane_dest_rows(self, table_row, start, end):
        """Physical pool row per logical position in [start, end);
        positions outside map out of range (nb*bs) so scatters DROP
        them — the shared-block copy-on-write guard (rows before
        ``start`` belong to radix-shared blocks this lane must never
        write)."""
        bs = self.kv_block_size
        nb = 1 + self._kv_pool.n_blocks
        pos = jnp.arange(self.cache_len)
        phys = table_row[jnp.clip(pos // bs, 0, self._kv_nblk_lane - 1)]
        return jnp.where((pos >= start) & (pos < end),
                         phys * bs + pos % bs, nb * bs)

    def _scatter_rows_tree(self, cache, cache_1, table_row, start, end):
        """Scatter the batch-1 LINEAR cache's rows [start, end) into the
        paged pool at ``table_row``'s blocks (traced helper shared by
        insert and preload; leaves pair by module path — only the leaf
        names differ between the two cache layouts).  int8 configs
        carry the per-row scales along the same row map: the pool
        stores exactly the bytes the batch-1 prefill quantized, which
        is what keeps int8 paged parity bitwise."""
        dest = self._lane_dest_rows(table_row, start, end)
        rename = {"key_pool": "key_cache", "value_pool": "value_cache",
                  "kv_pool_scales": "kv_scales"}
        flat_1 = {self._path_key(p): leaf for p, leaf
                  in jax.tree_util.tree_flatten_with_path(cache_1)[0]}

        def scatter(path, leaf):
            name = getattr(path[-1], "key", "")
            if name not in rename:
                return leaf
            src = flat_1[self._path_key(path[:-1]) + (rename[name],)]
            if name == "kv_pool_scales":
                # [..., 2, 1, C, kvh] → rows at axis -2 of the
                # flattened [..., 2, nb*bs, kvh] pool.
                src = jnp.squeeze(src, axis=-3)    # drop the batch-1 dim
                n_lead = leaf.ndim - 3             # dims before (nb, bs)
                flat = leaf.reshape(leaf.shape[:n_lead] + (-1,)
                                    + leaf.shape[-1:])
            else:
                src = jnp.squeeze(src, axis=-4)    # drop the batch-1 dim
                n_lead = leaf.ndim - 4
                flat = leaf.reshape(leaf.shape[:n_lead] + (-1,)
                                    + leaf.shape[-2:])
            idx = (slice(None),) * n_lead + (dest,)
            flat = flat.at[idx].set(src.astype(flat.dtype), mode="drop")
            return flat.reshape(leaf.shape)

        return jax.tree_util.tree_map_with_path(scatter, cache)

    @compile_site(buckets="slot-grid (shape-fixed per engine)",
                  donates=(1,), statics=(0,), max_compiles=4)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _paged_insert(self, cache, cache_1, slot, table_row, start,
                      true_len):
        """Paged-mode ``_insert``: scatter the prefilled rows [start,
        true_len) into the lane's blocks, install its block-table row,
        and pin its index to the TRUE prompt length (rows below
        ``start`` come from radix-shared blocks and are already
        there)."""
        cache = self._scatter_rows_tree(cache, cache_1, table_row,
                                        start, true_len)

        def pin(path, leaf):
            name = getattr(path[-1], "key", "")
            if name == "block_table":
                return leaf.at[..., slot, :].set(table_row)
            if name == "index":
                return leaf.at[..., slot].set(true_len)
            return leaf

        return jax.tree_util.tree_map_with_path(pin, cache)

    @compile_site(buckets="slot-grid (shape-fixed per engine)",
                  donates=(1,), statics=(0,), max_compiles=4)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _paged_preload(self, cache, cache_1, table_row, start, end):
        """Scatter a preloaded prefix's rows [start, end) into
        radix-held blocks — no lane: tables and indices are untouched
        (``start`` skips blocks the radix already caches — shared
        blocks are never rewritten, the COW rule)."""
        return self._scatter_rows_tree(cache, cache_1, table_row,
                                       start, end)

    @compile_site(buckets="slot-grid (shape-fixed per engine)",
                  donates=(), statics=(0, 3), max_compiles=4)
    @partial(jax.jit, static_argnums=(0, 3))
    def _gather_prefix(self, cache, table_row, draft, matched):
        """The inverse of ``_scatter_rows_tree``: read a lane's leading
        ``matched`` rows out of the pool into a fresh batch-1 LINEAR
        cache (index pinned to ``matched``), so the suffix prefill runs
        the exact piece programs the linear engine's ``preload_prefix``
        path runs — a prefix hit replaces recompute with this copy.
        Rows past ``matched`` gather whatever the lane's owned blocks
        hold — garbage the write-before-read prefill rule keeps
        invisible, exactly like the linear cache's stale rows."""
        bs = self.kv_block_size
        pos = jnp.arange(self.cache_len)
        rows = (table_row[jnp.clip(pos // bs, 0, self._kv_nblk_lane - 1)]
                * bs + pos % bs)
        rename = {"key_cache": "key_pool", "value_cache": "value_pool",
                  "kv_scales": "kv_pool_scales"}
        pools = {self._path_key(p): leaf for p, leaf
                 in jax.tree_util.tree_flatten_with_path(cache)[0]}
        struct = self._cache_struct(1, draft=draft)

        def build(path, s):
            name = getattr(path[-1], "key", "")
            if name == "index":
                return jnp.full(s.shape, matched, s.dtype)
            src = pools[self._path_key(path[:-1]) + (rename[name],)]
            if name == "kv_scales":
                # Pool [..., 2, nb, bs, kvh] → batch-1 [..., 2, 1, C,
                # kvh]: same row map, batch dim re-inserted at -3.
                n_lead = src.ndim - 3
                flat = src.reshape(src.shape[:n_lead] + (-1,)
                                   + src.shape[-1:])
            else:
                n_lead = src.ndim - 4
                flat = src.reshape(src.shape[:n_lead] + (-1,)
                                   + src.shape[-2:])
            take = jnp.take(flat, rows, axis=n_lead)
            return jnp.expand_dims(take, axis=n_lead).astype(s.dtype)

        return jax.tree_util.tree_map_with_path(build, struct)

    @compile_site(buckets="slot-grid (shape-fixed per engine)",
                  donates=(1,), statics=(0,), max_compiles=4)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _reset_lanes(self, cache, stale):
        """Point ``stale`` lanes' block tables at the scratch block and
        zero their indices: a retired/cancelled lane's blocks go back
        to the pool at harvest, but the overlap scheduler has one more
        garbage chunk for it in (or headed for) the device queue — this
        runs BEFORE that chunk, so its writes land in scratch instead
        of blocks someone else now owns."""
        def rst(path, leaf):
            name = getattr(path[-1], "key", "")
            if name == "block_table":
                return jnp.where(stale[:, None], 0, leaf)
            if name == "index":
                return jnp.where(stale, 0, leaf)
            return leaf

        return jax.tree_util.tree_map_with_path(rst, cache)

    @compile_site(buckets="slot-grid (the un-bucketed-prompt storm "
                          "surfaces HERE when prefill discipline "
                          "slips)",
                  donates=(2,), statics=(0,), max_compiles=4)
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _decode_chunk(self, variables, cache, tok, seeds, counts):
        """``chunk`` decode steps for all slots; one device round-trip.
        ``seeds``/``counts`` [slots]: each slot's sampling stream and
        how many tokens it has already drawn (greedy ignores both).
        Also returns the NEXT chunk's (tok, counts) carry — computed
        inside the same program so the overlap scheduler can chain
        chunks with zero extra dispatches (the sync path ignores
        them)."""
        def step(carry, j):
            cache, tok = carry
            with quantized_inference():
                logits, upd = self._model.apply(
                    dict(variables, cache=cache), tok[:, None],
                    mutable=["cache"])
            nxt = self._pick(logits[:, -1], seeds, counts + j).astype(
                tok.dtype)
            return (upd["cache"], nxt), nxt

        (cache, last), toks = jax.lax.scan(
            step, (cache, tok), jnp.arange(self.chunk))
        return (cache, jnp.moveaxis(toks, 0, 1),    # [slots, chunk]
                last, counts + self.chunk)

    # -- host-side loop ----------------------------------------------------

    @thread_role("handler", "driver", "main")
    def validate_request(self, prompt, max_new_tokens: int,
                         seed: Optional[int] = None,
                         resume_from: int = 0) -> list:
        """All of ``submit()``'s checks WITHOUT enqueuing; returns the
        normalized prompt (a list of ints).  Read-only, so the HTTP
        gateway's handler threads can reject bad requests (400) before
        handing admission to the single engine-owning driver thread —
        the engine's mutating calls stay single-threaded."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if seed is not None and not 0 <= seed < 2 ** 32:
            # Catch at submit: an out-of-range seed would OverflowError
            # inside run(), aborting every in-flight request.
            raise ValueError(f"seed must be a uint32, got {seed}")
        if not prompt:
            raise ValueError("empty prompt")
        if resume_from < 0 or resume_from >= len(prompt):
            # The resumed tail is part of the prompt, and at least one
            # ORIGINAL prompt token must remain under it.
            raise ValueError(
                f"resume_from must be in [0, len(prompt)), got "
                f"{resume_from} for a {len(prompt)}-token prompt")
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, got "
                             f"{max_new_tokens}")
        if len(prompt) + max_new_tokens > self.cache_len:
            raise ValueError(
                f"prompt {len(prompt)} + {max_new_tokens} new exceeds "
                f"cache_len={self.cache_len}")
        if self.paged:
            # Admission is keyed on BLOCKS: a request whose worst-case
            # block need exceeds the whole pool could never be granted
            # a lane — reject now instead of deadlocking the queue.
            need = -(-(len(prompt) + max_new_tokens)
                     // self.kv_block_size)
            if need > self._kv_pool.n_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks "
                    f"(block_size={self.kv_block_size}) but the pool "
                    f"has {self._kv_pool.n_blocks}")
        if self.hbm_budget_bytes is not None:
            # Projected BYTES alongside the free-blocks check: this
            # request's marginal device allocation is one batch-1
            # prefill cache pair — refuse admission when the live
            # ledger (pools, stored prefixes, in-flight prefills)
            # plus that pair cannot fit the declared budget.  An
            # engine whose POOL alone exceeds the budget is not
            # screened here: the pool allocator itself raises
            # MemoryBudgetError at first insert with the live set
            # diffed, which is the clearer error for a sizing bug.
            live = (memcheck.live_bytes(owner=self)
                    if memcheck.armed() else 0)
            projected = live + self._prefill_pair_bytes()
            if projected > self.hbm_budget_bytes:
                raise ValueError(
                    f"admission needs a projected {projected} bytes "
                    f"(live pools + one prefill cache pair) but "
                    f"hbm_budget_bytes={self.hbm_budget_bytes} — "
                    f"shrink --kv-pool-blocks/slots or raise the "
                    f"budget")
        if (not self._exact_prefill and self.prefill_chunk is None
                and not resume_from):
            # Catch at submit time: failing later inside run() would
            # drop this request silently and abort others mid-flight.
            # Only the SUFFIX after the longest preloaded prefix needs
            # a bucket — a long shared system prompt plus a short tail
            # is the feature's primary use (preload before submit: a
            # prefix loaded later cannot rescue an already-rejected
            # request).
            # Paged mode anchors the rule on operator-DECLARED preloads
            # (radix entries evict under pressure; admission chunks a
            # grown suffix, but validation must stay deterministic).
            # RESUMED requests are exempt: the original admission
            # already passed this policy bound, the resumed tail is the
            # request's own output, and ``_pieces_for`` chunks any span
            # into largest-bucket pieces (the long-preload mechanics) —
            # rejecting here would kill an accepted half-streamed
            # request as 'invalid' mid-failover.
            work = len(prompt) - (self._longest_declared_prefix(prompt)
                                  if self.paged
                                  else self._match_prefix(prompt)[0])
            if work > self.prompt_buckets[-1]:
                raise ValueError(
                    f"prompt length {len(prompt)} (suffix {work} after "
                    f"the longest preloaded prefix) exceeds the largest "
                    f"prefill bucket {self.prompt_buckets[-1]}")
        return prompt

    @thread_role("driver", "main")
    def submit(self, prompt, max_new_tokens: int,
               seed: Optional[int] = None, resume_from: int = 0) -> int:
        """Enqueue a request; returns its id (resolved by ``run()``).

        ``seed`` names the request's sampling stream (ignored under
        greedy); default: the request id — distinct per request,
        reproducible across identical engine sessions.

        ``resume_from=g`` declares the prompt's LAST ``g`` tokens to be
        this request's own earlier output (the failover re-admission
        contract): the rng counter starts at ``g`` instead of 0, so a
        seeded-sampling continuation lands exactly where the
        uninterrupted stream would have — the re-admitted request's
        output is the original's, minus the tokens already delivered.
        Greedy ignores the counter and resumes for free."""
        prompt = self.validate_request(prompt, max_new_tokens, seed,
                                       resume_from)
        rid = self._next_id
        self._next_id += 1
        self._queue.append(
            (rid, prompt, max_new_tokens,
             rid if seed is None else seed, resume_from))
        events.instant("engine/queued", rid=rid, prompt_len=len(prompt),
                       max_new=max_new_tokens)
        return rid

    @thread_role("driver", "main")
    def cancel(self, request_id: int) -> bool:
        """Abandon a live request: drop it from the queue, discard its
        staged partial prefill, or free its slot so the next refill
        reuses it (the gateway's deadline lever).  A freed slot's cache
        rows go stale-but-invisible — position masks hide them and the
        next ``_insert`` re-pins the slot index, the same rule stale
        rows already obey between ``run()`` cycles; a cancelled staged
        prefill frees its lane IMMEDIATELY (the partial batch-1 cache
        is simply dropped — it never touched the slot grid).  Returns
        False when the id is unknown or already finished (its output,
        if any, stays harvestable)."""
        for i, item in enumerate(self._queue):
            if item[0] == request_id:
                del self._queue[i]
                events.instant("engine/cancel", rid=request_id,
                               where="queued")
                return True
        for slot, task in self._staging.items():
            if task.request_id == request_id:
                if task.kv is not None:
                    # Partial prefill lived in the batch-1 cache only;
                    # the claim's blocks were never read — free them.
                    self._kv_release(task.kv)
                del self._staging[slot]
                events.instant("engine/cancel", rid=request_id,
                               where="staged")
                return True
        for slot, state in enumerate(self._slot_states):
            if state is not None and state.request_id == request_id:
                if self.paged:
                    # Prompt blocks stay radix-cached (inserted at
                    # finalize); the generated tail is dropped with
                    # the lane.
                    self._lane_release(slot)
                self._slot_states[slot] = None
                events.instant("engine/cancel", rid=request_id,
                               where="slot")
                return True
        return False

    def active_slots(self) -> int:
        """Slots currently occupied by a request — decoding or staged
        mid-prefill (occupancy gauge: a prefilling lane is reserved)."""
        return (sum(s is not None for s in self._slot_states)
                + len(self._staging))

    def staged_rids(self) -> tuple:
        """Request ids whose prefill is staged in a reserved lane —
        the driver's slot-grant signal for requests the decode
        snapshot cannot show yet (a staged lane is granted: no other
        request can claim it)."""
        return tuple(t.request_id for t in self._staging.values())

    def queue_depth(self) -> int:
        """Requests accepted but not yet in a slot."""
        return len(self._queue)

    def _cache_struct(self, batch: int, draft: bool = False,
                      grid: bool = False):
        """Memoized eval_shape of a cache tree: ``grid`` selects the
        slot-grid decode model (the paged pool + block tables when
        paging is on), otherwise the batch-1 LINEAR prefill model.  One
        trace per (draft, batch, grid) — re-tracing per request would
        put host latency in the serving loop."""
        key = (draft, batch, grid)
        shapes = self._cache_shapes.get(key)
        if shapes is None:
            if grid:
                model = self._draft_model if draft else self._model
            else:
                model = (self._draft_prefill_model if draft
                         else self._prefill_model)
            variables = (self._draft_variables if draft
                         else self._variables)

            def shape_fn(variables):
                with quantized_inference():
                    return model.apply(
                        variables, jnp.zeros((batch, 1), jnp.int32),
                        mutable=["cache"])[1]["cache"]

            shapes = jax.eval_shape(shape_fn, variables)
            self._cache_shapes[key] = shapes
        return shapes

    # Memory discipline (ttd-lint memcheck + TTD_MEMCHECK=1): THE
    # engine allocator — every cache tree this engine mints on device
    # comes through here (or through _admission_cache_1 below, whose
    # gather/copy paths mint the same batch-1 layout).  The pool split
    # mirrors what an operator budgets: the slot-grid pools (target
    # "kv_pool", draft "draft_pool") are owner-lifetime — allocated
    # once, alive until the engine dies, exact in the gauges — while
    # batch-1 prefill caches are leaf-lifetime transients (the charge
    # is the admission-time budget gate; donation threads the buffers
    # through the piece programs as successors the ledger cannot see).
    # Projection comes from the memoized cache eval_shape, so an
    # over-budget pool raises BEFORE any buffer exists.
    @memory_budget(
        pool=lambda self, batch, draft=False, grid=False:
            (("draft_pool" if draft else "kv_pool") if grid
             else ("draft_prefill" if draft else "prefill_cache")),
        budget_fn=lambda self, *a, **k: self.hbm_budget_bytes,
        project_fn=lambda self, batch, draft=False, grid=False:
            memcheck.tree_bytes(self._cache_struct(batch, draft, grid)),
        lifetime=lambda self, batch, draft=False, grid=False:
            ("owner" if grid else "leaf"))
    def _fresh_cache(self, batch: int, draft: bool = False,
                     grid: bool = False):
        """Zeroed cache tree for ``batch`` rows (target or draft model;
        ``grid``: the slot-grid decode layout vs the batch-1 linear
        prefill layout).  Prefill asks for a fresh batch-1 cache per
        request — donation consumes the buffers."""
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self._cache_struct(batch, draft, grid))

    def _pieces_for(self, m: int):
        """(piece_len, n_pieces) for prefilling an m-token span — THE
        piece-sizing rule for request suffixes and preloaded prefixes
        alike.  Bucket mode runs spans longer than the largest bucket
        as largest-bucket-sized pieces (appends at the running index,
        the same mechanics as chunked prefill), so long shared system
        prompts preload without a dedicated chunk setting."""
        if self._exact_prefill:
            return m, 1
        if self.prefill_chunk is not None:
            return self.prefill_chunk, -(-m // self.prefill_chunk)
        piece = _bucket_len(min(m, self.prompt_buckets[-1]),
                            self.prompt_buckets)
        return piece, -(-m // piece)

    def _run_target_piece(self, cache_1, padded, piece: int, i: int,
                          m: int, seed: int, rng0: int = 0):
        """Piece ``i`` of a target prefill — THE single source of the
        per-piece layout/local-idx rule, shared by atomic admission
        (``_prefill_tokens``) and the staged scheduler
        (``_advance_piece``) so the two paths stay byte-for-byte.
        ``rng0``: the first pick's rng counter (resume-from-token
        admission continues a stream; fresh requests pick at 0)."""
        toks = jnp.asarray(padded[:, i * piece:(i + 1) * piece])
        # local_idx only matters on the piece holding the last real
        # token (the final one).
        local = min(m - 1 - i * piece, piece - 1)
        return self._prefill_piece(self._variables, cache_1, toks,
                                   jnp.int32(max(local, 0)),
                                   jnp.uint32(seed), jnp.int32(rng0))

    def _run_draft_piece(self, d_cache_1, padded, piece: int, i: int):
        """Piece ``i`` of a draft prefill (same piece grid as the
        target's — both caches must hold identical row sets)."""
        toks = jnp.asarray(padded[:, i * piece:(i + 1) * piece])
        return self._draft_prefill_piece(self._draft_variables,
                                         d_cache_1, toks)

    def _prefill_tokens(self, work, *, seed: int, cache_1, draft: bool,
                        rng0: int = 0):
        """Append ``work`` to a batch-1 cache in compile-bounded pieces
        (shared by request prefill and prefix preload, target and
        draft).  Returns (cache, first_token) — ``first`` is the pick
        at the last REAL row (None for the draft, which only needs its
        KV rows)."""
        m = len(work)
        piece, n_pieces = self._pieces_for(m)
        padded = np.zeros((1, piece * n_pieces), np.int32)
        padded[0, :m] = work
        first = None
        for i in range(n_pieces):
            if draft:
                cache_1 = self._run_draft_piece(cache_1, padded,
                                                piece, i)
            else:
                cache_1, first = self._run_target_piece(
                    cache_1, padded, piece, i, m, seed, rng0)
        return cache_1, first

    @thread_role("main", "driver")
    def preload_prefix(self, tokens) -> None:
        """Prefill a shared prompt prefix ONCE; every later request
        whose prompt strictly extends it prefills only the suffix.

        The production lever for shared system prompts / few-shot
        preambles: the stored batch-1 cache is copied per request
        (donation-safe) and the suffix pieces append at the prefix's
        true position — causal masks and RoPE read positions from the
        per-slot index, so outputs are token-identical to a full
        prefill (pinned in tests/test_serving.py).  Under speculative
        serving the DRAFT model's prefix cache is stored alongside the
        target's (both prefill once, both reuse).  Restriction:
        dense-dispatch MoE prefills at the exact full length (routing
        capacity is length-dependent) and serves without prefix reuse.
        """
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        if not tokens:
            raise ValueError("empty prefix")
        if self._exact_prefill:
            raise ValueError(
                "prefix caching needs length-independent routing; "
                "dense-dispatch MoE prefills at the exact prompt length "
                "(dispatch='gmm' supports prefix caching)")
        n = len(tokens)
        if n >= self.cache_len:
            raise ValueError(
                f"prefix length {n} must leave cache room "
                f"(cache_len={self.cache_len})")
        # Pin the stored index to the TRUE prefix length: suffix
        # pieces must append at position n, not after the pad rows
        # (which stay harmless — overwritten before any read).
        def pin(path, leaf):
            if any(getattr(k, "key", "") == "index" for k in path):
                return jnp.full_like(leaf, n)
            return leaf

        with self._ctx(), events.span("prefill/prefix", tokens=n):
            cache_1, _ = self._prefill_tokens(
                tokens, seed=0, cache_1=self._fresh_cache(1),
                draft=False)
            cache_1 = jax.tree_util.tree_map_with_path(pin, cache_1)
            d_cache_1 = None
            if self._draft_model is not None:
                d_cache_1, _ = self._prefill_tokens(
                    tokens, seed=0,
                    cache_1=self._fresh_cache(1, draft=True), draft=True)
                d_cache_1 = jax.tree_util.tree_map_with_path(
                    pin, d_cache_1)
        # LRU bound: these entries hold device memory (a batch-1 cache
        # pair each) and used to accumulate forever — evict the least
        # recently MATCHED prefix past the limit.  ``_preloaded`` (the
        # paged path's validation anchor) is bounded in lockstep so the
        # host-side record cannot outgrow the limit either.
        with self._prefix_lock:
            self._prefix_caches[tuple(tokens)] = (cache_1, d_cache_1)
            self._prefix_caches.move_to_end(tuple(tokens))
            while len(self._prefix_caches) > self.prefix_cache_limit:
                evicted_key, _ = self._prefix_caches.popitem(last=False)
                self._preloaded.pop(evicted_key, None)
            if self.paged:
                self._preloaded[tuple(tokens)] = n
        # The STORED pair is a held-as-minted device tree (copied per
        # admission, freed at LRU eviction) — exactly the
        # leaf-lifetime contract, so the memcheck ledger tracks the
        # prefix store byte-exactly and an unbounded preload pattern
        # trips the budget here instead of OOMing later.
        memcheck.track(self, "prefix_cache", (cache_1, d_cache_1),
                       label=f"prefix{n}",
                       budget=self.hbm_budget_bytes)
        if self.paged:
            # Paged mode ALSO seeds the radix index with the prefix's
            # full blocks (scattered from the just-built cache — no
            # second prefill), so later requests share them through the
            # pool like any other radix hit; the stored batch-1 pair
            # keeps covering the sub-block tail (a prefix shorter than
            # one block has no shareable blocks at all).
            with self._ctx():
                self._seed_radix_from_cache(tokens, cache_1, d_cache_1)

    def _seed_radix_from_cache(self, tokens, cache_1, d_cache_1) -> None:
        """Scatter a preloaded prefix's FULL blocks from its batch-1
        cache into freshly allocated pool blocks and hand them to the
        radix index (tree-held: shared by every later matching request,
        LRU-evicted only under pressure)."""
        n = len(tokens)
        bs = self.kv_block_size
        m = n // bs                       # full, shareable blocks
        if m == 0:
            return                        # sub-block prefix: pair-only
        matched, shared = self._radix.match(tokens[:m * bs],
                                            allow_full=True)
        if matched >= m * bs:
            return                        # every full block is cached
        # Pin the already-cached leading blocks against the eviction
        # our own allocation below may trigger.
        for b in shared:
            self._kv_pool.ref(b)
        try:
            n_new = m - len(shared)
            fresh = self._kv_pool.alloc(n_new)
            if fresh is None:
                evicted = self._radix.evict_for(n_new)
                if evicted:
                    with self._stats_lock:
                        self.kv_stats["evictions"] += evicted
                    events.instant("kv/evict", blocks=evicted)
                fresh = self._kv_pool.alloc(n_new)
            if fresh is None:
                logger.warning(
                    "preload_prefix: KV pool too busy to share the "
                    "prefix's %d blocks (%d free); the batch-1 cache "
                    "still serves it", n_new,
                    self._kv_pool.free_blocks())
                return
            row = shared + fresh
            table_np = np.zeros((self._kv_nblk_lane,), np.int32)
            table_np[:len(row)] = row
            table_j = jnp.asarray(table_np)
            start, end = jnp.int32(matched), jnp.int32(m * bs)
            if self._cache is None:
                self._cache = self._fresh_cache(self.slots, grid=True)
            self._cache = self._paged_preload(self._cache, cache_1,
                                              table_j, start, end)
            if self._draft_model is not None:
                if self._d_cache is None:
                    self._d_cache = self._fresh_cache(
                        self.slots, draft=True, grid=True)
                self._d_cache = self._paged_preload(
                    self._d_cache, d_cache_1, table_j, start, end)
            self._radix.insert(tokens[:m * bs], lambda j: row[j])
            # The tree took one reference per NEW node; release the
            # allocation's own (a node already present keeps its
            # canonical block, so ours frees here).
            for b in fresh:
                self._kv_pool.deref(b)
        finally:
            for b in shared:
                self._kv_pool.deref(b)

    # Row-holding cache leaves, by batch-1 linear name, with the axis
    # their rows live on: the serialization manifest for KV handoff
    # (``kv_scales`` is [..., 2, 1, C, kvh] — rows at -2; key/value are
    # [..., 1, C, kvh, hd] — rows at -3).
    _KV_LEAF_ROW_AXIS = {"key_cache": -3, "value_cache": -3,
                         "kv_scales": -2}

    @thread_role("main", "driver")
    def export_prefix_kv(self, tokens):
        """Serialize the KV of ``tokens``' full leading blocks for a
        prefill→decode handoff: ``(meta, blob)``, or None when there is
        nothing exportable (linear cache, sub-block prompt, pool too
        busy to share).

        The prefill side of disaggregated serving: prefill the prompt's
        block-aligned head (``preload_prefix`` — the tested machinery,
        which also makes repeat prompts free on this worker), then
        gather those pool rows back out (``_gather_prefix``) and ship
        the bytes VERBATIM — the pool already stores the
        ``_quantize_kv_rows`` output, so the receiving pool installs
        bit-identical rows and the decode-side radix hit reproduces the
        exact local-prefill output.  At least one suffix token is left
        unexported (its logit picks the first generated token on the
        decode worker, same as any radix hit).  Mutates engine state —
        callers marshal onto the engine's owning thread
        (``EngineDriver.call``)."""
        if not self.paged or self._exact_prefill:
            return None
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        bs = self.kv_block_size
        m = max(0, (len(tokens) - 1) // bs)   # full blocks, head only
        if m == 0:
            return None
        head = tokens[:m * bs]
        matched, shared = self._radix.match(head, allow_full=True,
                                            record=False)
        if matched < m * bs:
            self.preload_prefix(head)
            matched, shared = self._radix.match(head, allow_full=True,
                                                record=False)
        if matched < m * bs or self._cache is None:
            return None               # pool too busy to share the head
        for b in shared:
            self._kv_pool.ref(b)
        try:
            table_np = np.zeros((self._kv_nblk_lane,), np.int32)
            table_np[:len(shared)] = shared
            table_j = jnp.asarray(table_np)
            with self._ctx(), events.span("kv/export", tokens=m * bs):
                leaves, blob = self._serialize_rows(table_j, m * bs)
        finally:
            for b in shared:
                self._kv_pool.deref(b)
        meta = {"tokens": head, "n": m * bs,
                "draft": self._draft_model is not None,
                "leaves": leaves}
        return meta, blob

    def _serialize_rows(self, table_j, n: int):
        """The ONE wire byte-recipe every KV-bearing frame ships
        (``KV_HANDOFF`` and ``MIGRATE``): gather the first ``n`` pool
        rows reachable through ``table_j`` into a batch-1 linear cache
        pair, slice each row-holding leaf, and concatenate contiguous
        bytes in path-sorted manifest order (the installer replays the
        manifest positionally).  Returns ``(leaves, blob)``.  Callers
        hold refs on (or own) the table's blocks and run on the
        engine-owning thread."""
        span = jnp.int32(n)
        pairs = [(False, self._gather_prefix(
            self._cache, table_j, False, span))]
        if self._draft_model is not None:
            pairs.append((True, self._gather_prefix(
                self._d_cache, table_j, True, span)))
        leaves, chunks = [], []
        for draft, cache_1 in pairs:
            flat = jax.tree_util.tree_flatten_with_path(cache_1)[0]
            for p, leaf in sorted(
                    flat, key=lambda pl: self._path_key(pl[0])):
                name = getattr(p[-1], "key", "")
                axis = self._KV_LEAF_ROW_AXIS.get(name)
                if axis is None:
                    continue
                idx = [slice(None)] * leaf.ndim
                idx[axis] = slice(0, n)
                arr = np.asarray(jax.device_get(leaf[tuple(idx)]))
                leaves.append({
                    "path": list(self._path_key(p)),
                    "draft": draft,
                    "dtype": arr.dtype.str,
                    "shape": list(arr.shape)})
                chunks.append(np.ascontiguousarray(arr).tobytes())
        return leaves, b"".join(chunks)

    @thread_role("main", "driver")
    def install_prefix_kv(self, meta, blob) -> int:
        """Install handed-off KV rows into this engine's pool + radix
        index; returns the warm-token count (0 = refused, benign — the
        request simply prefills locally with identical output).

        The decode side of the handoff: rebuild the batch-1 linear
        cache pair from the wire bytes (exact dtypes — the rows stay
        bit-identical to the sender's pool) and hand it to
        ``_seed_radix_from_cache``, the SAME path ``preload_prefix``
        seeds the radix through, so allocation, eviction pressure, COW
        and partial-failure semantics are all the tested ones.  Mutates
        engine state — callers marshal onto the engine's owning thread
        (``EngineDriver.call``)."""
        if not self.paged or self._exact_prefill:
            return 0
        tokens = [int(t) for t in meta.get("tokens", ())]
        n = int(meta.get("n", 0))
        bs = self.kv_block_size
        if n <= 0 or n % bs or n != len(tokens):
            raise ValueError(f"bad handoff span: n={n} over "
                             f"{len(tokens)} tokens (block_size={bs})")
        if n >= self.cache_len:
            raise ValueError(f"handoff span {n} exceeds "
                             f"cache_len={self.cache_len}")
        matched, _ = self._radix.match(tokens, allow_full=True,
                                       record=False)
        if matched >= n:
            return n                  # already warm — nothing to do
        if bool(meta.get("draft")) != (self._draft_model is not None):
            return 0   # speculative mismatch: both caches must hold
        #              # identical row sets, so refuse → local prefill
        arrays, off = {}, 0
        for leaf in meta.get("leaves", ()):
            dtype = np.dtype(leaf["dtype"])
            shape = tuple(int(d) for d in leaf["shape"])
            count = int(np.prod(shape)) if shape else 1
            end = off + count * dtype.itemsize
            if end > len(blob):
                raise ValueError(
                    f"handoff blob truncated: leaf {leaf['path']} "
                    f"needs bytes [{off}, {end}) of {len(blob)}")
            arrays[(bool(leaf.get("draft")), tuple(leaf["path"]))] = (
                np.frombuffer(blob, dtype, count, off).reshape(shape))
            off = end
        if off != len(blob):
            raise ValueError(f"handoff blob has {len(blob) - off} "
                             f"trailing bytes")

        def build_one(draft: bool):
            want = {pk: a for (d, pk), a in arrays.items()
                    if d is draft}

            def fill(path, leaf):
                name = getattr(path[-1], "key", "")
                if name == "index":
                    return jnp.full_like(leaf, n)
                axis = self._KV_LEAF_ROW_AXIS.get(name)
                arr = want.get(self._path_key(path))
                if axis is None or arr is None:
                    return leaf
                idx = [slice(None)] * leaf.ndim
                idx[axis] = slice(0, n)
                want_shape = tuple(leaf[tuple(idx)].shape)
                if arr.shape != want_shape or (arr.dtype
                                               != leaf.dtype):
                    raise ValueError(
                        f"handoff leaf {self._path_key(path)} is "
                        f"{arr.dtype}{list(arr.shape)}, this engine "
                        f"needs {leaf.dtype}{list(want_shape)}")
                return leaf.at[tuple(idx)].set(jnp.asarray(arr))

            return jax.tree_util.tree_map_with_path(
                fill, self._fresh_cache(1, draft=draft))

        with self._ctx(), events.span("kv/install", tokens=n):
            cache_1 = build_one(False)
            d_cache_1 = (build_one(True)
                         if self._draft_model is not None else None)
            self._seed_radix_from_cache(tokens, cache_1, d_cache_1)
        matched, _ = self._radix.match(tokens, allow_full=True,
                                       record=False)
        return matched

    @thread_role("main", "driver")
    def export_lane(self, request_id: int):
        """Serialize a live request's FULL migration state —
        ``(meta, blob)`` — or None when the id is unknown/finished.

        The source half of live mid-stream migration (the MIGRATE
        frame's payload).  ``meta["kind"]`` names where the request
        lived:

        - ``"lane"``: a decoding slot.  ``tokens`` is the authoritative
          prompt+generated history (a snapshot taken between engine
          steps, so it is always >= what any relay has delivered),
          ``remaining``/``seed``/``count`` restore the budget and the
          rng counter, and ``meta["kv"]`` + ``blob`` carry the lane's
          full-block pool rows in the exact ``KV_HANDOFF`` byte recipe
          (``_serialize_rows`` — int8 + scales, bit-identical rows) so
          the target resumes WITHOUT re-prefilling the head.  Rows are
          gathered from the lane's OWN block table: valid for
          ``[0, len(tokens) - 1)`` (the last sampled token was never
          fed back), hence the head stops at the last full block under
          that bound.  A linear-cache or sub-block lane exports with
          ``kv=None`` — the target re-prefills, which is exactly the
          failover path and stays bitwise by the same contract.
        - ``"staged"``: mid-admission in a reserved lane.  The partial
          batch-1 prefill is NOT shipped (pieces are cheap to redo and
          piece boundaries are engine-local); the staged cursor rides
          along so operators can see how far admission got.
        - ``"queued"``: accepted but never placed — parameters only.

        Export is read-only: the caller decides whether the move
        committed and then ``cancel()``s this side (the
        ``EngineDriver.export_lane`` wrapper does both atomically on
        the engine-owning thread, so no token can generate after the
        snapshot)."""
        for item in self._queue:
            if item[0] == request_id:
                _, prompt, max_new, seed, resume = item
                return {"kind": "queued", "prompt": list(prompt),
                        "max_new": int(max_new), "seed": int(seed),
                        "resume_from": int(resume), "kv": None}, b""
        for task in self._staging.values():
            if task.request_id == request_id:
                return {"kind": "staged", "prompt": list(task.prompt),
                        "max_new": int(task.max_new),
                        "seed": int(task.seed),
                        "resume_from": int(task.resume),
                        "cursor": int(task.cursor), "kv": None}, b""
        for slot, state in enumerate(self._slot_states):
            if state is None or state.request_id != request_id:
                continue
            meta = {"kind": "lane",
                    "tokens": [int(t) for t in state.tokens],
                    "remaining": int(state.remaining),
                    "last_token": int(state.last_token),
                    "seed": int(state.seed), "count": int(state.count),
                    "done": bool(state.done), "kv": None}
            blob = b""
            bs = self.kv_block_size
            m = max(0, (len(state.tokens) - 1) // bs)
            kv = (self._lane_kv[slot]
                  if self.paged and not self._exact_prefill else None)
            if kv is not None and m > 0 and self._cache is not None:
                head = [int(t) for t in state.tokens[:m * bs]]
                # The lane's claim already holds a ref on every block
                # in its table, and we run between steps on the
                # engine-owning thread — no eviction can race the
                # gather, so no extra pinning is needed.
                table_j = self._kv_table(kv)
                with self._ctx(), events.span("kv/export",
                                              tokens=m * bs):
                    leaves, blob = self._serialize_rows(table_j,
                                                        m * bs)
                meta["kv"] = {"tokens": head, "n": m * bs,
                              "draft": self._draft_model is not None,
                              "leaves": leaves}
            return meta, blob
        return None

    @thread_role("main", "driver")
    def install_lane(self, meta, blob) -> int:
        """Install a migrated lane's KV rows into this engine's pool +
        radix index; returns the warm-token count (0 = nothing to
        install or refused — benign: the re-admitted request simply
        prefills locally with identical output, the failover path).

        The target half of migration.  Only the KV needs engine-side
        installation — the request itself is re-admitted through the
        pool's normal resume-from-token placement, which radix-hits
        the rows seeded here (``install_prefix_kv`` → the SAME
        ``_seed_radix_from_cache`` path as a prefill→decode handoff,
        so allocation, eviction pressure and partial-failure semantics
        are all the tested ones).  Raises ValueError on a torn or
        lying manifest — the transport classifies that as a protocol
        failure of the one replica."""
        kv = meta.get("kv") if isinstance(meta, dict) else None
        if not kv or not blob:
            return 0
        return self.install_prefix_kv(dict(kv), blob)

    def _match_prefix(self, prompt, touch: bool = False):
        """Longest stored prefix the prompt strictly extends →
        (prefix_len, (target_cache, draft_cache_or_None));
        (0, None) when none applies.  ``touch`` refreshes the winner's
        LRU recency — admission paths (the driver loop) pass True;
        ``validate_request`` passes False.  Either way the walk holds
        ``_prefix_lock``: handler threads validate concurrently with
        the driver's LRU touches, and an OrderedDict mutated
        mid-iteration raises in the READER."""
        with self._prefix_lock:
            if not self._prefix_caches:
                return 0, None
            best, best_key, best_pair = 0, None, None
            for toks, pair in self._prefix_caches.items():
                m = len(toks)
                if best < m < len(prompt) and prompt[:m] == list(toks):
                    best, best_key, best_pair = m, toks, pair
            if touch and best_key is not None:
                self._prefix_caches.move_to_end(best_key)
            return best, best_pair

    def _longest_declared_prefix(self, prompt) -> int:
        """Longest PRELOADED prefix the prompt strictly extends — the
        paged path's validation anchor.  Validation must not consult
        the radix index (its entries evict under pressure, and
        admission handles a shrunk match by chunking the longer
        suffix); preloads are operator-declared, LRU-bounded like the
        linear pairs they parallel."""
        best = 0
        with self._prefix_lock:
            for toks, m in self._preloaded.items():
                if best < m < len(prompt) and prompt[:m] == list(toks):
                    best = m
        return best

    def _note_moe_prefill_len(self, n: int) -> None:
        if not self._exact_prefill or n in self._moe_prefill_lens:
            return
        self._moe_prefill_lens.add(n)
        if len(self._moe_prefill_lens) > 1:
            # Compile-storm hazard: MoE prefills at the EXACT length
            # (router capacity depends on it), so every distinct
            # prompt length is a new XLA program.  Warn once per
            # length; mitigation: pad/truncate prompts to a few
            # lengths host-side (MIGRATION.md §8).
            logger.warning(
                "MoE engine prefill compiling for new prompt length "
                "%d (%d distinct lengths so far — one program each; "
                "consider padding prompts to a few fixed lengths)",
                n, len(self._moe_prefill_lens))

    # -- paged-pool admission (block claims, prefix hits, eviction) --------

    def _kv_claim(self, rid: int, prompt, max_new: int):
        """Claim a lane's physical blocks: radix-match the prompt's
        block-aligned prefix (shared blocks, one extra ref each), then
        allocate the rest — evicting LRU retired radix entries under
        pressure.  Returns a ``serving_kv.LaneKV`` or None when the
        pool cannot supply the blocks (the request is REFUSED admission
        and keeps its queue place — blocks free as lanes retire; never
        a corrupted live lane)."""
        bs = self.kv_block_size
        need = -(-min(len(prompt) + max_new, self.cache_len) // bs)
        # A block-starved queue head retries this claim every engine
        # step: on retries, skip the flight-recorder span and the radix
        # hit stats (one admission must not read as thousands), same
        # per-request rule as the refusal counter below.
        retry = rid == self._kv_refused_rid
        matched, shared = ((0, []) if self._exact_prefill
                           else self._radix.match(prompt,
                                                  record=not retry))
        # Ref the shared blocks BEFORE allocating: eviction only takes
        # refcount-1 leaves, so the refs pin the matched path against
        # the very eviction the allocation below may trigger.
        for b in shared:
            self._kv_pool.ref(b)
        n_owned = need - len(shared)
        with (contextlib.nullcontext() if retry
              else events.span("kv/alloc", rid=rid, blocks=n_owned,
                               shared=len(shared))):
            owned = self._kv_pool.alloc(n_owned)
            if owned is None:
                evicted = self._radix.evict_for(n_owned)
                if evicted:
                    with self._stats_lock:
                        self.kv_stats["evictions"] += evicted
                    events.instant("kv/evict", blocks=evicted)
                owned = self._kv_pool.alloc(n_owned)
        if owned is None:
            for b in shared:
                self._kv_pool.deref(b)
            # Count one refusal PER REQUEST, not per retry: the queue
            # head is re-claimed every serve_step while it waits, and a
            # per-attempt count would report thousands of "refusals"
            # for one waiting request.
            if self._kv_refused_rid != rid:
                self._kv_refused_rid = rid
                with self._stats_lock:
                    self.kv_stats["alloc_refusals"] += 1
                events.instant("kv/refused", rid=rid, blocks=n_owned)
            return None
        if matched:
            with self._stats_lock:
                self.kv_stats["prefix_hits"] += 1
                self.kv_stats["prefix_hit_tokens"] += matched
            events.instant("kv/prefix_hit", rid=rid, tokens=matched)
        return serving_kv.LaneKV(request_id=rid, matched=matched,
                                 shared=shared, owned=owned)

    def _kv_release(self, kv) -> None:
        """Drop the lane's references; blocks nobody else (radix or a
        sharing lane) holds return to the free list."""
        for b in kv.blocks():
            self._kv_pool.deref(b)

    def _kv_table(self, kv):
        """The lane's device block-table row (scratch-padded)."""
        return jnp.asarray(
            np.asarray(kv.table(self._kv_nblk_lane), np.int32))

    def _lane_claim(self, slot: int, kv, prompt) -> None:
        """Install a lane's claim at insert time and feed the radix
        index with the prompt's full blocks (their rows are valid —
        prefill wrote [0, len(prompt)) before this), so LATER requests
        with the same prefix share them immediately."""
        self._lane_kv[slot] = kv
        self._stale_slots.discard(slot)
        if not self._exact_prefill:
            table = kv.table(self._kv_nblk_lane)
            self._radix.insert(prompt, lambda j: table[j])

    def _lane_release(self, slot: int, tokens=None) -> None:
        """Retire/cancel a lane's claim: optionally extend the radix
        index with the request's generated full blocks (rows are valid
        up to ``len(tokens) - 1`` — the final token was never fed back,
        so its row may not exist), then drop the lane's refs and mark
        the lane stale so the next dispatch points its table at
        scratch before any in-flight garbage chunk can land in freed
        blocks."""
        kv = self._lane_kv[slot]
        if kv is None:
            return
        if tokens is not None and not self._exact_prefill:
            bs = self.kv_block_size
            keep = tokens[:((len(tokens) - 1) // bs) * bs]
            table = kv.table(self._kv_nblk_lane)
            self._radix.insert(keep, lambda j: table[j])
        self._kv_release(kv)
        self._lane_kv[slot] = None
        self._stale_slots.add(slot)

    @dispatch_critical
    def _flush_stale_lanes(self) -> None:
        """Zero retired/cancelled lanes' block-table rows before the
        next decode program (their freed blocks may already belong to
        someone else; the overlap garbage chunk must write scratch)."""
        if not self.paged or not self._stale_slots:
            return
        if self._cache is None:
            self._stale_slots.clear()
            return
        mask = np.zeros((self.slots,), bool)
        for s in self._stale_slots:
            mask[s] = True
        jm = jnp.asarray(mask)
        self._cache = self._reset_lanes(self._cache, jm)
        if self._d_cache is not None:
            self._d_cache = self._reset_lanes(self._d_cache, jm)
        self._stale_slots.clear()

    def _admission_match(self, kv, prompt):
        """(pre_len, pre_pair) for a paged admission: the radix match
        (kv.matched, gather path) unless a STORED preload pair covers
        more — sub-block prefix tails only the batch-1 pair can
        represent (a prefix shorter than a block has no shareable
        blocks; a 20-token prefix at block 16 shares one block and
        copies the 4-token tail).  Suffix prefill piece sizing follows
        ``pre_len`` exactly as on the linear path."""
        pre_len, pre_pair = kv.matched, None
        if not self._exact_prefill:
            lin_len, lin_pair = self._match_prefix(prompt, touch=True)
            if lin_len > pre_len:
                pre_len, pre_pair = lin_len, lin_pair
        return pre_len, pre_pair

    @memory_budget(
        pool=lambda self, pre_pair, kv, table_j, draft:
            ("draft_prefill" if draft else "prefill_cache"),
        budget_fn=lambda self, *a, **k: self.hbm_budget_bytes,
        project_fn=lambda self, pre_pair, kv, table_j, draft:
            memcheck.tree_bytes(self._cache_struct(1, draft=draft)),
        lifetime="leaf")
    def _admission_cache_1(self, pre_pair, kv, table_j, draft: bool):
        """The batch-1 cache a request's suffix prefill appends to:
        fresh when nothing matched; the stored prefix cache's copy when
        a preloaded pair won the match; a pool gather of the
        radix-matched rows otherwise (copy instead of recompute — same
        downstream piece programs every way).  All three paths mint
        the same batch-1 layout, which is what the @memory_budget
        projection charges (the nested ``_fresh_cache`` call defers to
        this outermost charge — the sanitizer's re-entrancy rule)."""
        if pre_pair is not None:
            return jax.tree.map(jnp.copy, pre_pair[1 if draft else 0])
        if not self.paged or kv is None or kv.matched == 0:
            return self._fresh_cache(1, draft=draft)
        cache = self._d_cache if draft else self._cache
        if cache is None:          # defensive: matched blocks imply a
            cache = self._fresh_cache(self.slots, draft=draft,
                                      grid=True)
            if draft:              # built grid, so keep it
                self._d_cache = cache
            else:
                self._cache = cache
        return self._gather_prefix(cache, table_j, draft,
                                   jnp.int32(kv.matched))

    def kv_blocks_total(self) -> int:
        """Allocatable physical blocks in the paged pool (0 when the
        linear cache is serving — the truthful scrape)."""
        return self._kv_pool.n_blocks if self.paged else 0

    def kv_blocks_in_use(self) -> int:
        """Blocks currently referenced (live lanes + radix cache)."""
        return self._kv_pool.blocks_in_use() if self.paged else 0

    def kv_bytes_in_use(self) -> int:
        """Referenced pool blocks in device BYTES (live lanes + radix
        cache at the real per-block row cost) — the occupancy half of
        ``kv_pool_bytes()``'s constant capacity, relayed per worker in
        stats frames and shown per replica in /healthz."""
        return self._kv_pool.bytes_in_use() if self.paged else 0

    def kv_pool_bytes(self) -> int:
        """Device bytes the paged KV pools pin across layers (target +
        draft; int8 scale pools included; 0 = linear cache).  Constant
        per engine — the pool never grows — so scrape threads read a
        plain int; the ``--kv-pool-blocks`` oversizing lever budgets
        against this."""
        return self._kv_pool_bytes

    def _prefill_pair_bytes(self) -> int:
        """Bytes of one batch-1 prefill cache pair (target + draft) —
        the marginal device allocation an admission mints; memoized
        off the same cache eval_shape the pool-bytes gauge uses
        (host-only trace, no device work)."""
        if self._prefill_bytes_memo is None:
            n = memcheck.tree_bytes(self._cache_struct(1))
            if self._draft_model is not None:
                n += memcheck.tree_bytes(self._cache_struct(1,
                                                            draft=True))
            self._prefill_bytes_memo = n
        return self._prefill_bytes_memo

    def hbm_autosized_bytes(self) -> int:
        """The HBM budget the autosize solve installed (0 when the
        engine was hand-sized or the solve was killed) — the
        ``ttd_engine_hbm_autosized_bytes`` gauge feed.  Written once at
        construction, so scrape threads read a plain int."""
        return self._hbm_autosized

    def _solve_hbm_autosize(self, config, draft_config):
        """``kv_pool_blocks='auto'``: solve (kv_pool_blocks,
        hbm_budget_bytes) EXACTLY from the device's reported HBM and
        the memcheck projection.  Grid cache bytes are linear in the
        block count (pool rows scale; block tables, indices, and
        scratch rows don't), so two eval_shape probes (n=1, n=2) give
        the intercept/slope, and the solve takes the largest n with

            grid_bytes(n) + batch-1 prefill transients
                <= avail * (1 - hbm_headroom)

        The right-hand side becomes ``hbm_budget_bytes``, so the
        ``@memory_budget`` ledger enforces the same arithmetic the
        solve used: an autosized engine's own pools and admission
        transients fit by construction (zero MemoryBudgetError — the
        exactness tests/test_spec_adaptive.py pins).  Host-only
        eval_shape traces; nothing allocates here.  Called from the
        ctor BEFORE ``_cache_shapes`` exists, hence the direct
        eval_shape instead of ``_cache_struct``."""
        avail = _device_hbm_bytes()
        if avail is None:
            raise ValueError(
                "kv_pool_blocks='auto' needs a device memory report "
                "(device.memory_stats()) or TTD_HBM_BYTES=<bytes>")

        def tree_b(model, variables, batch):
            def shape_fn(v):
                with quantized_inference():
                    return model.apply(
                        v, jnp.zeros((batch, 1), jnp.int32),
                        mutable=["cache"])[1]["cache"]

            return memcheck.tree_bytes(
                jax.eval_shape(shape_fn, variables))

        def grid_bytes(n):
            b = tree_b(
                _decode_model(config, self.cache_len, slot_decode=True,
                              paged_kv_blocks=1 + n,
                              kv_block_size=self.kv_block_size),
                self._variables, self.slots)
            if draft_config is not None:
                b += tree_b(
                    _decode_model(draft_config, self.cache_len,
                                  slot_decode=True,
                                  paged_kv_blocks=1 + n,
                                  kv_block_size=self.kv_block_size),
                    self._draft_variables, self.slots)
            return b

        trans = tree_b(self._prefill_model, self._variables, 1)
        if draft_config is not None:
            trans += tree_b(self._draft_prefill_model,
                            self._draft_variables, 1)
        b1, b2 = grid_bytes(1), grid_bytes(2)
        slope, intercept = b2 - b1, 2 * b1 - b2
        usable = int(avail * (1.0 - self._hbm_headroom))
        n = (usable - intercept - trans) // slope
        if n < 1:
            raise ValueError(
                f"kv_pool_blocks='auto': no pool fits — device HBM "
                f"{avail} bytes minus {self._hbm_headroom:.0%} headroom "
                f"leaves {usable}, but one block of pools plus batch-1 "
                f"prefill transients needs "
                f"{intercept + slope + trans} (shrink hbm_headroom, "
                f"slots, or cache_len)")
        return int(n), usable

    def fused_attn(self) -> bool:
        """Whether the decode programs were compiled with the fused
        paged-attention kernel (False on CPU, under a mesh, with the
        linear cache, or when TTD_NO_FUSED_ATTN killed it)."""
        return self._fused_attn

    def _spec_depth(self) -> int:
        """Draft depth the NEXT speculative round dispatches at: the
        controller's pick under adaptive speculation, else the fixed
        ``speculative_k`` (0 on a plain-decode engine).  Host int —
        read BEFORE the dispatch window opens."""
        with self._stats_lock:
            ctrl = self._spec_ctrl
            return self._spec_k if ctrl is None else ctrl.depth()

    @thread_role("handler", "driver")
    def spec_depth(self) -> int:
        """Scrape face of ``_spec_depth`` — the
        ``ttd_engine_spec_depth`` gauge feed (a fixed engine reports
        its constant k; a plain-decode engine reports 0)."""
        return self._spec_depth()

    @thread_role("handler", "driver")
    def spec_accepted_tokens(self) -> int:
        """Cumulative draft tokens the target ACCEPTED across
        speculative rounds (the numerator of the fleet acceptance
        rate; ``ttd_engine_spec_accepted_tokens_total``)."""
        with self._stats_lock:
            return self.spec_stats["drafted_accepted"]

    @thread_role("handler", "driver")
    def spec_drafted_tokens(self) -> int:
        """Cumulative draft tokens PROPOSED across speculative rounds
        (k per slot-round at the round's dispatched depth — the
        denominator; ``ttd_engine_spec_drafted_tokens_total``)."""
        with self._stats_lock:
            return self.spec_stats["drafted"]

    def spec_telemetry(self) -> dict:
        """Per-depth controller telemetry (rounds, acceptance EWMA) —
        bench/debug surface; {} for fixed-depth engines."""
        with self._stats_lock:
            ctrl = self._spec_ctrl
            return {} if ctrl is None else ctrl.telemetry()

    @thread_role("handler", "driver")
    def kv_prefix_hit_tokens(self) -> int:
        """Cumulative prompt tokens whose prefill was skipped via
        radix prefix hits (the prefill-compute-saved counter; the
        `/metrics` FnCounter samples this from handler threads at
        scrape time, so the read locks)."""
        with self._stats_lock:
            return self.kv_stats["prefix_hit_tokens"]

    @thread_role("handler", "driver")
    def kv_evictions(self) -> int:
        """Cumulative blocks LRU-evicted from the radix cache under
        allocation pressure (scrape-sampled: the read locks)."""
        with self._stats_lock:
            return self.kv_stats["evictions"]

    def _fill_free_slots(self):
        """ATOMIC admission — the ``prefill_budget=0`` /
        ``TTD_NO_INTERLEAVE`` path: a popped request's entire prefill
        runs inline before control returns, so active decode lanes
        wait it out (``prefill_stats['stall_s']`` measures that
        head-of-line time; the staged path keeps it ~0)."""
        stalled = any(s is not None for s in self._slot_states)
        prefilled = False
        t0 = time.perf_counter()
        for slot in range(self.slots):
            # Keep popping until this slot is OCCUPIED or the queue is
            # dry: a request that resolves at prefill time (max_new<=1
            # or first-token EOS) must not leave the slot idle for a
            # whole decode chunk while runnable work waits.
            while self._slot_states[slot] is None and self._queue:
                rid, prompt, max_new, seed, resume = \
                    self._queue.popleft()
                if max_new == 0:
                    self._outputs[rid] = list(prompt)
                    continue
                n = len(prompt)
                kv = table_j = None
                if self.paged:
                    kv = self._kv_claim(rid, prompt, max_new)
                    if kv is None:
                        # No blocks: refuse admission, keep FIFO order
                        # (the request takes its place back; blocks
                        # free as lanes retire).
                        self._queue.appendleft(
                            (rid, prompt, max_new, seed, resume))
                        if prefilled and stalled:
                            with self._stats_lock:
                                self.prefill_stats["stall_s"] += (
                                    time.perf_counter() - t0)
                        return
                    table_j = self._kv_table(kv)
                    pre_len, pre_pair = self._admission_match(kv, prompt)
                else:
                    # Prefix reuse: prefill only the suffix on a copy
                    # of the stored cache(s) (piece sizing follows the
                    # suffix).
                    pre_len, pre_pair = self._match_prefix(prompt,
                                                           touch=True)
                work = prompt[pre_len:]
                self._note_moe_prefill_len(n)
                prefilled = True
                with self._ctx(), events.span(
                        "prefill/request", rid=rid, tokens=len(work)):
                    cache_1 = self._admission_cache_1(
                        pre_pair, kv, table_j, draft=False)
                    cache_1, first = self._prefill_tokens(
                        work, seed=seed, cache_1=cache_1, draft=False,
                        rng0=resume)
                first = int(first)
                state = _SlotState(request_id=rid, remaining=max_new - 1,
                                   tokens=list(prompt) + [first],
                                   last_token=first, seed=seed,
                                   count=resume + 1)
                if (max_new == 1 or (self.eos_id is not None
                                     and first == self.eos_id)):
                    # Resolved at prefill — and checked BEFORE the draft
                    # prefill, which such a request would waste.  Its
                    # blocks were never written: hand them straight
                    # back.
                    if kv is not None:
                        self._kv_release(kv)
                    self._outputs[rid] = state.tokens
                    continue  # slot still free: try the next request
                with self._ctx(), events.span("prefill/insert", rid=rid):
                    if self._draft_model is not None:
                        d_cache_1 = self._admission_cache_1(
                            pre_pair, kv, table_j, draft=True)
                        d_cache_1, _ = self._prefill_tokens(
                            work, seed=seed, cache_1=d_cache_1,
                            draft=True)
                    if self._cache is None:
                        self._cache = self._fresh_cache(self.slots,
                                                        grid=True)
                    if self.paged:
                        # Scatter everything past the SHARED blocks
                        # (kv.matched, not pre_len — a preload pair's
                        # sub-block tail lives only in cache_1 and must
                        # land in this lane's owned blocks).
                        self._cache = self._paged_insert(
                            self._cache, cache_1, jnp.int32(slot),
                            table_j, jnp.int32(kv.matched),
                            jnp.int32(n))
                    else:
                        self._cache = self._insert(
                            self._cache, cache_1, jnp.int32(slot),
                            jnp.int32(len(prompt)))
                    if self._draft_model is not None:
                        if self._d_cache is None:
                            self._d_cache = self._fresh_cache(
                                self.slots, draft=True, grid=True)
                        if self.paged:
                            self._d_cache = self._paged_insert(
                                self._d_cache, d_cache_1,
                                jnp.int32(slot), table_j,
                                jnp.int32(kv.matched), jnp.int32(n))
                        else:
                            self._d_cache = self._insert(
                                self._d_cache, d_cache_1,
                                jnp.int32(slot), jnp.int32(len(prompt)))
                if kv is not None:
                    self._lane_claim(slot, kv, prompt)
                self._slot_states[slot] = state
                # Overlap bookkeeping: the next dispatch must splice
                # this slot's host-known token/count over the device
                # carry (which still holds the previous tenant's).
                self._refills.add(slot)
                events.instant("slot/insert", rid=rid, slot=slot)
        if prefilled and stalled:
            with self._stats_lock:
                self.prefill_stats["stall_s"] += time.perf_counter() - t0

    # -- staged prefill (decode-priority chunked-prefill scheduling) -------

    def _stage_from_queue(self) -> None:
        """Claim free lanes for queued requests as staged-prefill
        tasks.  Host-only bookkeeping — no device work happens until a
        budget installment advances the task — so this is safe to call
        anywhere in the step (it is the staged path's analog of the
        slot-claiming half of ``_fill_free_slots``)."""
        for slot in range(self.slots):
            if not self._queue:
                return
            if (self._slot_states[slot] is not None
                    or slot in self._staging):
                continue
            while self._queue:
                rid, prompt, max_new, seed, resume = \
                    self._queue.popleft()
                if max_new == 0:
                    self._outputs[rid] = list(prompt)
                    continue
                kv = table_j = None
                if self.paged:
                    kv = self._kv_claim(rid, prompt, max_new)
                    if kv is None:
                        # No blocks: refuse the claim and stop staging
                        # entirely (FIFO — nothing behind may jump the
                        # head; blocks free as lanes retire).
                        self._queue.appendleft(
                            (rid, prompt, max_new, seed, resume))
                        return
                    table_j = self._kv_table(kv)
                    pre_len, pre_pair = self._admission_match(kv, prompt)
                else:
                    pre_len, pre_pair = self._match_prefix(prompt,
                                                           touch=True)
                work = prompt[pre_len:]
                self._note_moe_prefill_len(len(prompt))
                m = len(work)
                piece, n_pieces = self._pieces_for(m)
                padded = np.zeros((1, piece * n_pieces), np.int32)
                padded[0, :m] = work
                self._staging[slot] = _PrefillTask(
                    request_id=rid, prompt=list(prompt),
                    max_new=max_new, seed=seed, work=work,
                    padded=padded, piece=piece, n_pieces=n_pieces,
                    resume=resume, pre_pair=pre_pair, kv=kv,
                    table=table_j)
                with self._stats_lock:
                    self.prefill_stats["staged_requests"] += 1
                break

    def _finalize_prefill(self, slot: int, task: _PrefillTask) -> None:
        """Both caches complete: insert into the slot grid and flip the
        lane to decoding (caller holds ``self._ctx()``)."""
        first = task.first_host
        state = _SlotState(request_id=task.request_id,
                           remaining=task.max_new - 1,
                           tokens=list(task.prompt) + [first],
                           last_token=first, seed=task.seed,
                           count=task.resume + 1)
        if self._cache is None:
            self._cache = self._fresh_cache(self.slots, grid=True)
        n = len(task.prompt)
        if self.paged:
            self._cache = self._paged_insert(
                self._cache, task.cache_1, jnp.int32(slot), task.table,
                jnp.int32(task.kv.matched), jnp.int32(n))
        else:
            self._cache = self._insert(self._cache, task.cache_1,
                                       jnp.int32(slot), jnp.int32(n))
        if self._draft_model is not None:
            if self._d_cache is None:
                self._d_cache = self._fresh_cache(self.slots, draft=True,
                                                 grid=True)
            if self.paged:
                self._d_cache = self._paged_insert(
                    self._d_cache, task.d_cache_1, jnp.int32(slot),
                    task.table, jnp.int32(task.kv.matched), jnp.int32(n))
            else:
                self._d_cache = self._insert(self._d_cache,
                                             task.d_cache_1,
                                             jnp.int32(slot),
                                             jnp.int32(n))
        if task.kv is not None:
            self._lane_claim(slot, task.kv, task.prompt)
        # Staging is cleared BEFORE the slot state is set: the gateway's
        # metrics thread reads active_slots() (= decoding + staged)
        # concurrently, and this order keeps a torn read at or below
        # the true occupancy instead of reporting slots_in_use >
        # slots_total (the overlap_ratio() torn-read rule).
        del self._staging[slot]
        self._slot_states[slot] = state
        self._refills.add(slot)        # next dispatch splices host carry
        events.instant("slot/insert", rid=task.request_id, slot=slot)

    def _advance_piece(self, slot: int, task: _PrefillTask) -> int:
        """Run ONE installment of ``task`` — the next target (then
        draft) prefill piece, exactly the program ``_prefill_tokens``
        would have run at this position, plus the finalize/insert when
        it was the last — and return its token cost.  The per-request
        piece programs, their order, and the rng inputs are identical
        to atomic admission, so outputs are bitwise-identical; only the
        scheduling between OTHER lanes' decode chunks differs."""
        with self._ctx(), events.span(
                "prefill/piece", rid=task.request_id,
                piece=task.cursor + task.d_cursor,
                n_pieces=task.n_pieces):
            if task.cursor < task.n_pieces:
                if task.cache_1 is None:
                    task.cache_1 = self._admission_cache_1(
                        task.pre_pair, task.kv, task.table, draft=False)
                task.cache_1, task.first = self._run_target_piece(
                    task.cache_1, task.padded, task.piece, task.cursor,
                    len(task.work), task.seed, task.resume)
                task.cursor += 1
                if task.cursor == task.n_pieces:
                    # Materializing the first token blocks the host on
                    # this piece — the in-flight decode chunk (enqueued
                    # AHEAD of it) keeps the device busy through the
                    # wait.
                    first = int(task.first)
                    task.first_host = first
                    if (task.max_new == 1
                            or (self.eos_id is not None
                                and first == self.eos_id)):
                        # Resolved at prefill — before the draft
                        # prefill, which such a request would waste
                        # (the atomic path's rule).  Its blocks were
                        # never written: hand them straight back.
                        if task.kv is not None:
                            self._kv_release(task.kv)
                        self._outputs[task.request_id] = (
                            list(task.prompt) + [first])
                        del self._staging[slot]
                    elif self._draft_model is None:
                        self._finalize_prefill(slot, task)
                return task.piece
            # Target done, request unresolved: draft pieces.
            if task.d_cache_1 is None:
                task.d_cache_1 = self._admission_cache_1(
                    task.pre_pair, task.kv, task.table, draft=True)
            task.d_cache_1 = self._run_draft_piece(
                task.d_cache_1, task.padded, task.piece, task.d_cursor)
            task.d_cursor += 1
            if task.d_cursor == task.n_pieces:
                self._finalize_prefill(slot, task)
            return task.piece

    def _advance_prefills(self, hidden: bool) -> None:
        """Advance staged prefills by at most ``prefill_budget`` tokens
        (default: one piece) in request-arrival order.  ``hidden``: a
        decode chunk is already in flight AHEAD of this work on the
        device queue, so decoding lanes lose no cadence to it and no
        stall is charged.  With no lane decoding there is nobody to
        stall, so the budget is waived and admission runs at full
        speed (TTFT at session start matches atomic admission)."""
        self._stage_from_queue()
        if not self._staging:
            return
        decoding = any(s is not None for s in self._slot_states)
        t0 = time.perf_counter()
        spent = 0
        while self._staging:
            slot = next(iter(self._staging))
            spent += self._advance_piece(slot, self._staging[slot])
            with self._stats_lock:
                self.prefill_stats["installments"] += 1
            if slot not in self._staging:
                # Resolved or inserted: restage so a freed lane keeps
                # the budget flowing to the next queued request.
                self._stage_from_queue()
            if decoding and (self.prefill_budget is None
                             or spent >= self.prefill_budget):
                break
        if decoding and not hidden:
            with self._stats_lock:
                self.prefill_stats["stall_s"] += time.perf_counter() - t0

    @thread_role("handler", "driver")
    def prefill_stall_s(self) -> float:
        """Cumulative seconds decode lanes spent blocked behind
        admission prefill (wall time of prefill work run while >= 1
        lane was decoding with no successor chunk in flight to hide
        it).  Grows with every long admission on the atomic path;
        collapses to ~0 with interleaving on.  The gateway exposes it
        as ``ttd_engine_prefill_stall_seconds`` — scraped from handler
        threads, so the read locks."""
        with self._stats_lock:
            return self.prefill_stats["stall_s"]

    def _consume(self, state, tokens) -> None:
        """Append generated tokens to a slot's request, enforcing the
        budget and EOS — the ONE termination rule for chunked and
        speculative harvests alike."""
        for t in tokens:
            t = int(t)
            state.tokens.append(t)
            state.last_token = t
            state.count += 1
            state.remaining -= 1
            if (state.remaining <= 0
                    or (self.eos_id is not None and t == self.eos_id)):
                state.done = True
                break

    def _retire_if_done(self, slot, state):
        if state.done:
            if self.paged:
                # Feed the radix index with the finished request's
                # generated full blocks (a follow-up turn extending
                # this conversation hits warm KV), then free the rest.
                self._lane_release(slot, tokens=state.tokens)
            self._outputs[state.request_id] = state.tokens
            self._slot_states[slot] = None
            events.instant("slot/retire", rid=state.request_id,
                           slot=slot, tokens=len(state.tokens))

    def _harvest(self, toks: np.ndarray, rids=None):
        """``rids`` (overlap mode): the slot->request map captured at
        dispatch — a slot whose occupant changed since (retired and
        refilled, or cancelled) must NOT consume this chunk's tokens;
        they belong to the previous tenant and are trimmed here."""
        for slot, state in enumerate(self._slot_states):
            if state is None:
                continue
            if rids is not None and state.request_id != rids[slot]:
                continue
            self._consume(state, toks[slot])
            self._retire_if_done(slot, state)

    def _harvest_spec(self, emit, emitted, next_tok, accepted, k,
                      rids=None):
        """Consume each slot's emitted prefix from a speculative round
        (variable per slot; budget/EOS via the shared consume rule),
        tracking acceptance stats.  The round's bonus token is the last
        emitted one, so a surviving slot's ``last_token`` already holds
        ``next_tok`` after consuming.  ``k``: the depth the round was
        DISPATCHED at (recorded in the in-flight dict — under adaptive
        speculation the current pick may already differ); it sizes the
        drafted-token denominator and feeds the controller's
        acceptance observation.  ``rids``: the overlap trim guard,
        same rule as ``_harvest``."""
        del next_tok  # == emit[slot, emitted-1], consumed above
        with self._stats_lock:
            self.spec_stats["rounds"] += 1  # engine, not slot-rounds
        n_slots = acc_sum = 0
        for slot, state in enumerate(self._slot_states):
            if state is None:
                continue
            if rids is not None and state.request_id != rids[slot]:
                continue
            before = len(state.tokens)
            self._consume(state, emit[slot, :int(emitted[slot])])
            n_slots += 1
            acc_sum += int(accepted[slot])
            with self._stats_lock:
                self.spec_stats["slot_rounds"] += 1
                self.spec_stats["drafted"] += k
                self.spec_stats["drafted_accepted"] += int(accepted[slot])
                self.spec_stats["emitted"] += len(state.tokens) - before
            self._retire_if_done(slot, state)
        if self._spec_ctrl is not None:
            # One observation per harvested round, aggregated over the
            # slots that survived the trim guard (a fully-trimmed
            # garbage round still advances the dwell clock — the
            # controller's decisions stay a pure function of the
            # request stream).  Wall time is NOT fed here: depth
            # choices must be deterministic from acceptance alone.
            with self._stats_lock:
                self._spec_ctrl.observe(k * n_slots, acc_sum)

    def pending(self) -> int:
        """Requests not yet finished (queued + staged mid-prefill +
        decoding)."""
        return (len(self._queue) + len(self._staging)
                + sum(s is not None for s in self._slot_states))

    def progress(self) -> dict:
        """Token COUNTS so far per in-flight request, ``{request_id:
        len(prompt + generated)}`` — the O(slots) poll for TTFT/pace
        tracking (``snapshot()`` copies whole token lists; benches
        polling every step want this instead)."""
        return {s.request_id: len(s.tokens)
                for s in self._slot_states if s is not None}

    def snapshot(self) -> dict:
        """Tokens generated SO FAR for every in-flight request,
        ``{request_id: [prompt + generated]}`` — the streaming view
        between ``serve_step()`` calls (tokens arrive chunk-wise; a
        finished request leaves the snapshot and is returned by the
        step that completed it).  Copies, so callers may mutate."""
        return {s.request_id: list(s.tokens)
                for s in self._slot_states if s is not None}

    # -- async decode pipelining (one-chunk lookahead) ---------------------

    @dispatch_critical
    def _carry_arrays(self):
        """The next dispatch's (tok, counts): the device-resident carry
        from the previous chunk, with host values spliced in for slots
        refilled since (``jnp.where`` only ENQUEUES — still no sync).
        Retired-but-unrefilled slots keep garbage carry and decode
        garbage, exactly as idle slots already do on the sync path."""
        if self._carry is None:
            # First dispatch of the session: everything is host-known.
            tok = np.zeros((self.slots,), np.int32)
            counts = np.zeros((self.slots,), np.int32)
            for slot, state in enumerate(self._slot_states):
                if state is not None:
                    tok[slot] = state.last_token
                    counts[slot] = state.count
            self._refills.clear()
            return jnp.asarray(tok), jnp.asarray(counts)
        tok, counts = self._carry
        if self._refills:
            mask = np.zeros((self.slots,), bool)
            tok_h = np.zeros((self.slots,), np.int32)
            cnt_h = np.zeros((self.slots,), np.int32)
            for slot in self._refills:
                state = self._slot_states[slot]
                if state is None:      # refilled then cancelled
                    continue
                mask[slot] = True
                tok_h[slot] = state.last_token
                cnt_h[slot] = state.count
            jmask = jnp.asarray(mask)
            tok = jnp.where(jmask, jnp.asarray(tok_h), tok)
            counts = jnp.where(jmask, jnp.asarray(cnt_h), counts)
            self._refills.clear()
        return tok, counts

    @dispatch_critical
    def _dispatch_chunk(self) -> None:
        """Enqueue one decode chunk (or speculative round) for ALL
        slots from the device-resident carry.  No host sync: the call
        returns while the device may still be computing the PREVIOUS
        chunk — the successor simply queues behind it.  Captures the
        dispatch-time slot->request map the harvest's trim guard
        needs."""
        seeds = np.zeros((self.slots,), np.uint32)
        rids: list = [None] * self.slots
        for slot, state in enumerate(self._slot_states):
            if state is not None:
                seeds[slot] = state.seed
                rids[slot] = state.request_id
        # Depth for THIS round: the controller's pick (adaptive) or the
        # fixed k.  Host ints end to end — read before the dispatch
        # window opens (the controller is _stats_lock-guarded; the
        # window must stay conversion- and contention-free).
        k = self._spec_depth()
        with self._ctx(), events.span(
                "decode/dispatch",
                active=sum(r is not None for r in rids),
                fused=self._fused_tag, spec_k=k):
            # Retired/cancelled lanes' tables must point at scratch
            # BEFORE this chunk: their freed blocks may already be
            # reallocated, and this chunk decodes them as garbage.
            self._flush_stale_lanes()
            tok, counts = self._carry_arrays()
            jseeds = jnp.asarray(seeds)
            if self._draft_model is not None:
                (self._cache, self._d_cache, emit, emitted, next_tok,
                 acc, counts_next) = self._spec_round(
                    self._variables, self._draft_variables, self._cache,
                    self._d_cache, tok, jseeds, counts, k)
                # Continuing slots consumed exactly ``emitted`` tokens,
                # so the device advances their rng counters itself —
                # the property that lets round N+1 enqueue before round
                # N's host copy exists.
                self._carry = (next_tok, counts_next)
                self._inflight = {"spec": True, "rids": rids, "k": k,
                                  "emit": emit, "emitted": emitted,
                                  "next_tok": next_tok, "acc": acc}
            else:
                (self._cache, toks, last,
                 counts_next) = self._decode_chunk(
                    self._variables, self._cache, tok, jseeds, counts)
                self._carry = (last, counts_next)
                self._inflight = {"spec": False, "rids": rids,
                                  "toks": toks}
        with self._stats_lock:
            self.overlap_stats["chunks"] += 1

    @dispatch_critical
    def _skip_eager_dispatch(self) -> bool:
        """Whether to fall back to harvest-first for this one step:
        when EVERY active slot certainly retires in the in-flight chunk
        (budget exhaustion is host-predictable — ``remaining`` is
        known; EOS is not), an eager successor would be garbage end to
        end — the tail chunk of a session, or a mass-retirement
        boundary where the whole next chunk should decode refills
        instead.

        A SINGLE retiring lane keeps eager dispatch: its garbage costs
        one lane-chunk (~chunk/slots of device work, often zero when
        the queue is empty — the chunk is lockstep across slots), which
        measures cheaper than surrendering the overlapped host pass
        (policy A/B'd on the CPU mesh; revisit on silicon).

        Horizons: a plain chunk emits exactly ``chunk`` tokens per
        lane, so ``remaining <= chunk`` is certain retirement; a
        speculative round GUARANTEES only one emitted token (every
        draft rejected), so only ``remaining <= 1`` is certain —
        anything looser would surrender the overlap for up to k+1
        rounds at every batch tail."""
        horizon = (1 if self._draft_model is not None else self.chunk)
        certain = [s.remaining <= horizon
                   for s in self._slot_states if s is not None]
        return bool(certain) and all(certain)

    def _harvest_prev(self, inf: dict, overlapped: bool) -> None:
        """Materialize the previous chunk's host copy (this blocks
        until THAT chunk finishes — when ``overlapped``, the successor
        is already enqueued and keeps the device busy through the wait
        and the host passes that follow) and consume it under the
        dispatch-time rid guard.  Only the post-materialization host
        pass is timed into ``overlap_stats``: the block inside
        ``np.asarray`` is device time, not host-harvest time, and would
        drown the ratio."""
        rids = inf["rids"]
        with events.span("decode/wait", overlapped=overlapped):
            if inf["spec"]:
                args = (np.asarray(inf["emit"]),
                        np.asarray(inf["emitted"]),
                        np.asarray(inf["next_tok"]),
                        np.asarray(inf["acc"]))
            else:
                toks = np.asarray(inf["toks"])
        t0 = time.perf_counter()
        with events.span("decode/harvest", overlapped=overlapped):
            if inf["spec"]:
                self._harvest_spec(*args, inf["k"], rids=rids)
            else:
                self._harvest(toks, rids=rids)
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self.overlap_stats["harvest_s"] += dt
            if overlapped:
                self.overlap_stats["overlapped_harvests"] += 1
                self.overlap_stats["overlapped_harvest_s"] += dt

    @thread_role("handler", "driver")
    def overlap_ratio(self) -> float:
        """Fraction of host harvest wall time spent with a successor
        chunk concurrently in flight — the host-stall share the
        lookahead hides (0.0 under TTD_NO_OVERLAP/overlap=False).
        The gateway exposes it as ``ttd_engine_overlap_ratio``.

        Scraped from the gateway's metrics thread while the driver
        harvests: the pair is read under ``_stats_lock`` (and the
        writer updates both fields under it), so a scrape can no
        longer land between the denominator and numerator bumps and
        report a torn ratio."""
        with self._stats_lock:
            num = self.overlap_stats["overlapped_harvest_s"]
            total = self.overlap_stats["harvest_s"]
        if total <= 0.0:
            return 0.0
        return min(1.0, num / total)

    @thread_role("driver", "main")
    def serve_step(self) -> dict:
        """ONE service iteration: refill free slots from the queue, run
        one decode chunk, harvest — then hand control back, so callers
        can ``submit()`` new requests between steps (online serving: the
        queue never has to be complete up front).  Returns the requests
        that FINISHED this step, ``{request_id: tokens}`` (possibly
        empty); poll ``pending()`` for completion.

        With ``overlap`` on (the default), the step is PIPELINED: the
        successor chunk is dispatched from the device-resident carry
        BEFORE the in-flight chunk's host copy is touched, so stop
        detection, refills, and the caller's streaming/deadline passes
        (which run between ``serve_step`` calls — a chunk stays in
        flight across the return) all hide under device compute.  Stop
        decisions lag one chunk; the harvest trims the overshoot, so
        outputs are bitwise-identical to the synchronous path.  Note a
        finished session leaves one garbage chunk in flight — harmless,
        discarded by the next cycle's trim guard.

        With ``interleave`` on (the default), admission is STAGED:
        after the eager dispatch, at most ``prefill_budget`` tokens of
        staged prefill advance (enqueued behind the in-flight chunk),
        so a long prompt's admission spreads across steps while decode
        chunks for occupied lanes keep flowing every step.  The kill
        switch (``prefill_budget=0`` / ``TTD_NO_INTERLEAVE=1``)
        restores atomic admission byte-for-byte."""
        if not self.overlap:
            return self._serve_step_sync()
        if not self.interleave:
            return self._serve_step_overlap_atomic()
        prev, self._inflight = self._inflight, None
        # DECODE PRIORITY: the successor chunk for occupied lanes goes
        # onto the device queue before any admission work, so active
        # lanes never wait behind a new prompt's prefill.
        dispatched = False
        if (any(s is not None for s in self._slot_states)
                and not self._skip_eager_dispatch()):
            self._dispatch_chunk()          # device busy through the
            dispatched = True               # host passes below
        # One budget installment of admission, queued BEHIND the chunk
        # just dispatched (or behind ``prev``, still in flight) — the
        # gap it can add to an active lane is bounded by the budget.
        self._advance_prefills(hidden=dispatched or prev is not None)
        if prev is not None:
            self._harvest_prev(prev, overlapped=dispatched)
        # Lanes the harvest freed stage immediately (host-only) so
        # their first installment rides the next step's budget.
        self._stage_from_queue()
        if not dispatched and any(s is not None
                                  for s in self._slot_states):
            # Nothing was in flight to hide this pass behind (first
            # step of a session / a harvest-first fallback step /
            # post-idle restart): dispatch now so the NEXT step's
            # harvest overlaps.
            self._dispatch_chunk()
        out, self._outputs = self._outputs, {}
        return out

    def _serve_step_overlap_atomic(self) -> dict:
        """The pipelined step with ATOMIC admission — the path
        ``prefill_budget=0`` / ``TTD_NO_INTERLEAVE=1`` restores,
        byte-for-byte the pre-staged-prefill scheduling (pinned by
        tests/test_serving_overlap.py)."""
        prev, self._inflight = self._inflight, None
        if self._queue and any(s is None for s in self._slot_states):
            # Requests that arrived since the last harvest (the online
            # pattern: callers submit between steps) take their free
            # lanes BEFORE the eager dispatch, so they ride the very
            # next chunk — their prefills enqueue behind the in-flight
            # chunk, still overlapped.  Without this, a freed lane
            # would idle one extra chunk per turnaround.
            self._fill_free_slots()
        dispatched = False
        if (any(s is not None for s in self._slot_states)
                and not self._skip_eager_dispatch()):
            self._dispatch_chunk()          # device busy through the
            dispatched = True               # host passes below
        if prev is not None:
            self._harvest_prev(prev, overlapped=dispatched)
        self._fill_free_slots()
        if not dispatched and any(s is not None
                                  for s in self._slot_states):
            # Nothing was in flight to hide this pass behind (first
            # step of a session / a harvest-first fallback step /
            # post-idle restart): dispatch now so the NEXT step's
            # harvest overlaps.
            self._dispatch_chunk()
        out, self._outputs = self._outputs, {}
        return out

    def _serve_step_sync(self) -> dict:
        """The synchronous path ``TTD_NO_OVERLAP``/``overlap=False``
        restores: dispatch one chunk, block on its host copy, harvest —
        the device idles through every host pass (the PROFILE.md
        host-stall), but scheduling decisions never lag.  Staged
        admission still applies here unless ITS kill switch is also
        thrown: prefill advances at most ``prefill_budget`` tokens
        before the chunk, so active lanes' inter-chunk gap stays
        budget-bounded even without the lookahead."""
        if self.interleave:
            self._advance_prefills(hidden=False)
        else:
            self._fill_free_slots()
        # (No active slots == everything resolved at prefill time or
        # nothing queued: skip the decode, just drain what finished.)
        if any(s is not None for s in self._slot_states):
            tok = np.zeros((self.slots,), np.int32)
            seeds = np.zeros((self.slots,), np.uint32)
            counts = np.zeros((self.slots,), np.int32)
            n_active = 0
            for slot, state in enumerate(self._slot_states):
                if state is not None:
                    tok[slot] = state.last_token
                    seeds[slot] = state.seed
                    counts[slot] = state.count
                    n_active += 1
            if self._draft_model is not None:
                k = self._spec_depth()
                with self._ctx(), events.span(
                        "decode/dispatch", active=n_active,
                        fused=self._fused_tag, spec_k=k):
                    self._flush_stale_lanes()
                    (self._cache, self._d_cache, emit, emitted,
                     next_tok, acc, _) = self._spec_round(
                        self._variables, self._draft_variables,
                        self._cache, self._d_cache, jnp.asarray(tok),
                        jnp.asarray(seeds), jnp.asarray(counts), k)
                # decode/wait is the device block, decode/harvest the
                # host pass — same split as the overlap path, so the
                # two paths' traces are comparable span for span.
                with events.span("decode/wait", overlapped=False):
                    args = (np.asarray(emit), np.asarray(emitted),
                            np.asarray(next_tok), np.asarray(acc))
                with events.span("decode/harvest", overlapped=False):
                    self._harvest_spec(*args, k)
            else:
                with self._ctx(), events.span(
                        "decode/dispatch", active=n_active,
                        fused=self._fused_tag):
                    self._flush_stale_lanes()
                    self._cache, toks, _, _ = self._decode_chunk(
                        self._variables, self._cache, jnp.asarray(tok),
                        jnp.asarray(seeds), jnp.asarray(counts))
                with events.span("decode/wait", overlapped=False):
                    toks = np.asarray(toks)
                with events.span("decode/harvest", overlapped=False):
                    self._harvest(toks)
        out, self._outputs = self._outputs, {}
        return out

    @thread_role("main", "driver")
    def run(self) -> dict:
        """Serve every submitted request to completion; returns
        ``{request_id: [prompt + generated tokens]}``.  (A loop over
        ``serve_step()`` — use that directly for online serving.)"""
        out: dict = {}
        while self.pending():
            out.update(self.serve_step())
        return out
