"""Test infrastructure: multi-process clusters without real hardware.

Rebuilds the reference's distributed-test playbook (SURVEY.md §4):
``MultiProcessRunner`` (``distribute/multi_process_runner.py:107``) →
``MultiProcessRunner`` here; in-process fake clusters + ``MockOsEnv``
(``multi_worker_test_base.py:123,579``) → per-child env dicts; logical-
device splitting (``test_util.py:131``) → per-process virtual CPU devices.
"""

from tensorflow_train_distributed_tpu.testing.multiprocess import (  # noqa: F401
    MultiProcessRunner,
    ProcessResult,
    UnexpectedExitError,
    free_ports,
    tf_config_env,
)
