"""Child bootstrap for ``MultiProcessRunner`` workers.

Argv: ``target("module:function") rank payload_json``.  Configures the CPU
backend *before* any device API call (the interpreter may have imported
jax already via sitecustomize — env vars are too late, ``jax.config`` is
not), joins the cluster per the env the runner injected, runs the worker
fn, and emits its JSON result on stdout behind ``TTD_RESULT:``.
"""

import importlib
import json
import os
import sys


def main() -> int:
    target, rank_s, payload_json = sys.argv[1], sys.argv[2], sys.argv[3]
    rank = int(rank_s)
    payload = json.loads(payload_json)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        set_cpu_device_count,
    )

    set_cpu_device_count(int(os.environ.get("TTD_TEST_LOCAL_DEVICES", "2")))

    if os.environ.get("TTD_TEST_INIT_DISTRIBUTED") == "1":
        from tensorflow_train_distributed_tpu.runtime.distributed import (
            initialize_distributed,
        )

        initialize_distributed()

    mod_name, _, fn_name = target.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    result = fn(rank, **payload)
    print("TTD_RESULT:" + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
