"""Multi-process localhost clusters for distributed tests.

TPU-native rebuild of the reference's test rig (SURVEY.md §4): real
subprocesses (not forks — a forked XLA runtime is undefined behavior) each
running a named worker function on a virtual CPU backend, joined into one
cluster via ``jax.distributed.initialize`` against a localhost coordinator
— the same coordination service a real multi-host TPU pod uses, so
collectives, process_allgather, and multi-host checkpointing execute their
true code paths.  Mirrors ``MultiProcessRunner``'s contract: per-task env
injection (``TF_CONFIG`` included, via ``tf_config_env``), captured
stdout/stderr, timeout detection, and fault injection by killing workers
(``SubprocessTimeoutError`` / ``UnexpectedSubprocessExitError`` analogs).

Worker functions are addressed as ``"module:function"`` and must be
importable in the child (test modules are put on ``PYTHONPATH``
automatically).  The child bootstrap is ``testing._child``; results come
back as a JSON line on stdout.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Optional, Sequence

_RESULT_TAG = "TTD_RESULT:"


class UnexpectedExitError(RuntimeError):
    """A worker died (crash or injected kill) — reference
    ``UnexpectedSubprocessExitError`` analog."""

    def __init__(self, results):
        self.results = results
        detail = "\n".join(
            f"--- rank {r.rank} rc={r.returncode} ---\n{r.stderr[-2000:]}"
            for r in results if r.returncode != 0)
        super().__init__(f"worker process(es) failed:\n{detail}")


class TimeoutError_(RuntimeError):
    """Cluster did not finish in time (``SubprocessTimeoutError`` analog)."""


@dataclasses.dataclass
class ProcessResult:
    rank: int
    returncode: Optional[int]
    stdout: str
    stderr: str
    value: Any = None  # the worker fn's JSON-serializable return


def free_ports(n: int) -> list[int]:
    """Reserve n distinct free TCP ports (bind-then-release)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def tf_config_env(cluster: dict[str, Sequence[str]], task_type: str,
                  task_index: int) -> dict[str, str]:
    """A ``TF_CONFIG`` JSON env var for one task — the reference's cluster
    spec format (``tfconfig_cluster_resolver.py:48``), built per-child so
    the real process env is never mutated (``MockOsEnv`` analog)."""
    return {"TF_CONFIG": json.dumps({
        "cluster": {k: list(v) for k, v in cluster.items()},
        "task": {"type": task_type, "index": task_index},
    })}


class MultiProcessRunner:
    """Launch N workers; join them; deliver per-rank results.

    Each worker runs ``target`` = ``"module:function"`` as
    ``fn(rank, **payload)`` on a ``local_devices``-device CPU backend.
    With ``init_distributed`` (default) the children form one JAX cluster
    (global device count = N × local_devices).
    """

    def __init__(
        self,
        target: str,
        num_processes: int,
        *,
        payload: Optional[dict] = None,
        env_per_rank: Optional[Sequence[dict[str, str]]] = None,
        local_devices: int = 2,
        init_distributed: bool = True,
        timeout: float = 300.0,
    ):
        self.target = target
        self.num_processes = num_processes
        self.payload = payload or {}
        self.env_per_rank = env_per_rank or [{} for _ in range(num_processes)]
        self.local_devices = local_devices
        self.init_distributed = init_distributed
        self.timeout = timeout
        self._procs: list[subprocess.Popen] = []
        self._coordinator = f"127.0.0.1:{free_ports(1)[0]}"

    def start(self) -> "MultiProcessRunner":
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        for rank in range(self.num_processes):
            env = dict(os.environ)
            env.update(self.env_per_rank[rank])
            # Children must resolve the cluster from env exactly as a real
            # launch would (runtime.distributed resolution order).
            if self.init_distributed and "TF_CONFIG" not in env:
                env.update(
                    TTD_COORDINATOR=self._coordinator,
                    TTD_NUM_PROCESSES=str(self.num_processes),
                    TTD_PROCESS_ID=str(rank),
                )
            env["TTD_TEST_LOCAL_DEVICES"] = str(self.local_devices)
            env["TTD_TEST_INIT_DISTRIBUTED"] = (
                "1" if self.init_distributed else "0")
            # Make the caller's test modules importable in the child.
            extra_path = [repo_root] + [
                p for p in sys.path if p.endswith("tests")]
            env["PYTHONPATH"] = os.pathsep.join(
                extra_path + [env.get("PYTHONPATH", "")])
            self._procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "tensorflow_train_distributed_tpu.testing._child",
                 self.target, str(rank), json.dumps(self.payload)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env, cwd=repo_root,
            ))
        return self

    def terminate(self, rank: int, sig: int = signal.SIGKILL) -> None:
        """Fault injection: kill one worker (reference process-kill tests)."""
        self._procs[rank].send_signal(sig)

    def join(self, *, expect_failure: bool = False) -> list[ProcessResult]:
        deadline = time.monotonic() + self.timeout
        results: list[ProcessResult] = []
        for rank, p in enumerate(self._procs):
            remaining = max(0.1, deadline - time.monotonic())
            try:
                out, err = p.communicate(timeout=remaining)
            except subprocess.TimeoutExpired:
                for q in self._procs:
                    if q.poll() is None:
                        q.kill()
                out, err = p.communicate()
                results.append(ProcessResult(rank, None, out, err))
                raise TimeoutError_(
                    f"rank {rank} exceeded {self.timeout}s; stderr tail:\n"
                    f"{err[-2000:]}")
            value = None
            for line in out.splitlines():
                if line.startswith(_RESULT_TAG):
                    value = json.loads(line[len(_RESULT_TAG):])
            results.append(ProcessResult(rank, p.returncode, out, err, value))
        if not expect_failure and any(r.returncode != 0 for r in results):
            raise UnexpectedExitError(results)
        return results

    def run(self, *, expect_failure: bool = False) -> list[ProcessResult]:
        return self.start().join(expect_failure=expect_failure)
