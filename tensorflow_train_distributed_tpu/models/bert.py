"""BERT-base masked-LM pretraining — reference config[2].

The reference runs this under ParameterServerStrategy (coordinator + workers
+ ps, SURVEY.md §3.3); its own north star retires that for synchronous SPMD
("ParameterServerStrategy → DTensor SPMD"), which is exactly this module on
a dp(×tp) mesh: embedding/attention/MLP weights carry logical axes instead
of ShardedVariable round-robin placement, and the async closure queue
becomes the ordinary jitted step.

Architecture: post-LN encoder, learned positions, GELU FFN, MLM head with
transform + tied embedding logits + bias (BERT-base: L12 H768 A12 I3072).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models import layers as L
from tensorflow_train_distributed_tpu.ops.losses import (
    fold_sample_weight, softmax_cross_entropy,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_positions: int = 512
    dropout_rate: float = 0.1
    dtype: object = jnp.float32
    # HF-exact compatibility knobs (all default to the lean TPU-first
    # encoder; ``import_hf.import_bert`` requires them on so an HF
    # ``BertForMaskedLM`` state dict is representable bit-exactly):
    # q/k/v/out projection biases, token-type (segment) embeddings, and
    # the post-sum embedding LayerNorm.
    attention_bias: bool = False
    type_vocab_size: int = 0
    embed_layer_norm: bool = False
    layer_norm_eps: float = 1e-6  # flax default; HF checkpoints use 1e-12
    exact_gelu: bool = False      # erf GELU (HF) vs tanh approximation
    # HF configures attention-probability dropout separately from hidden
    # dropout; None keeps the single-rate convention.
    attention_dropout_rate: Optional[float] = None
    # Activation checkpointing per encoder layer (nn.remat): trades
    # recompute for activation memory at large batch/seq.
    remat: bool = False


def _gelu(cfg: "BertConfig"):
    if cfg.exact_gelu:
        return lambda x: nn.gelu(x, approximate=False)
    return nn.gelu


BERT_PRESETS = {
    "bert_base": BertConfig(),
    "bert_large": BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                             intermediate_size=4096),
    "bert_tiny": BertConfig(vocab_size=256, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dropout_rate=0.0),
}


class EncoderLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        attn_dropout = (cfg.dropout_rate
                        if cfg.attention_dropout_rate is None
                        else cfg.attention_dropout_rate)
        attn = L.MultiHeadAttention(
            num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            dtype=cfg.dtype,
            dropout_rate=attn_dropout,
            use_bias=cfg.attention_bias,
            name="attention",
        )(x, deterministic=deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, epsilon=cfg.layer_norm_eps,
                         name="attn_ln")(x + attn)
        mlp = L.MlpBlock(
            hidden=cfg.intermediate_size, dtype=cfg.dtype,
            dropout_rate=cfg.dropout_rate, name="mlp",
            activation=_gelu(cfg),
        )(x, deterministic=deterministic)
        return nn.LayerNorm(dtype=cfg.dtype, epsilon=cfg.layer_norm_eps,
                            name="mlp_ln")(x + mlp)


class BertEncoder(nn.Module):
    config: BertConfig = BertConfig()

    def setup(self):
        cfg = self.config
        self.embed = L.Embed(cfg.vocab_size, cfg.hidden_size,
                             dtype=cfg.dtype, name="token_embed")
        self.pos_embed = self.param(
            "pos_embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_positions, cfg.hidden_size),
        )
        if cfg.type_vocab_size:
            self.type_embed = self.param(
                "type_embedding",
                nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), (None, "embed")),
                (cfg.type_vocab_size, cfg.hidden_size),
            )
        if cfg.embed_layer_norm:
            self.embed_ln = nn.LayerNorm(
                dtype=cfg.dtype, epsilon=cfg.layer_norm_eps,
                name="embed_ln")
        # nn.remat preserves param names — HF-imported and previously
        # trained checkpoints load unchanged either way.
        layer_cls = (nn.remat(EncoderLayer, static_argnums=(2,))
                     if cfg.remat else EncoderLayer)
        self.encoder_layers = [
            layer_cls(cfg, name=f"layer_{i}")
            for i in range(cfg.num_layers)
        ]
        self.mlm_transform = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                      name="mlm_transform")
        self.mlm_ln = nn.LayerNorm(dtype=cfg.dtype,
                                   epsilon=cfg.layer_norm_eps,
                                   name="mlm_ln")
        self.mlm_bias = self.param(
            "mlm_bias",
            nn.with_logical_partitioning(nn.initializers.zeros, ("vocab",)),
            (cfg.vocab_size,),
        )

    def __call__(self, input_ids, *, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        seq_len = input_ids.shape[1]
        x = self.embed(input_ids)
        x = x + self.pos_embed[None, :seq_len].astype(cfg.dtype)
        if cfg.type_vocab_size:
            if token_type_ids is None:  # single-segment default
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + jnp.take(self.type_embed.astype(cfg.dtype),
                             token_type_ids, axis=0)
        if cfg.embed_layer_norm:
            x = self.embed_ln(x)
        for layer in self.encoder_layers:
            x = layer(x, deterministic)  # positional: remat static argnum
        # MLM head: transform → tied-embedding logits + bias.
        h = _gelu(cfg)(self.mlm_transform(x))
        h = self.mlm_ln(h)
        logits = self.embed.attend(h) + self.mlm_bias.astype(cfg.dtype)
        return nn.with_logical_constraint(
            logits, ("batch", "length", "vocab"))


class BertMlmTask:
    """Masked-LM objective over ``SyntheticMLM``-shaped batches."""

    report_perplexity = True  # evaluate() adds exp(mean masked loss)

    def __init__(self, config: BertConfig = BertConfig()):
        self.config = config
        self.model = BertEncoder(config)

    def init_variables(self, rng, batch):
        return self.model.init(rng, batch["input_ids"])

    def loss_fn(self, params, model_state, batch, rng, train):
        logits = self.model.apply(
            {"params": params}, batch["input_ids"],
            token_type_ids=batch.get("token_type_ids"),
            deterministic=not train,
            rngs={"dropout": rng} if train else {},
        ).astype(jnp.float32)
        weights = fold_sample_weight(batch, batch["labels"].shape,
                                     batch["mask_weights"])
        loss, acc = softmax_cross_entropy(
            logits, batch["labels"], weights=weights)
        # loss_weight: Task contract — lets gradient accumulation combine
        # microbatches as the true masked-token-weighted global mean.
        # Unclamped per fold_sample_weight's contract (the loss
        # denominator stays clamped inside softmax_cross_entropy;
        # recombination multiplies a garbage-0 loss by weight 0).
        w_total = weights.sum()
        return loss, ({"mlm_accuracy": acc, "loss_weight": w_total},
                      model_state)

    def predict_fn(self, params, model_state, batch):
        """MLM logits (Trainer.predict contract)."""
        del model_state
        return self.model.apply({"params": params}, batch["input_ids"],
                                token_type_ids=batch.get("token_type_ids"),
                                deterministic=True)


def make_task(config: BertConfig = BERT_PRESETS["bert_base"]) -> BertMlmTask:
    return BertMlmTask(config)
