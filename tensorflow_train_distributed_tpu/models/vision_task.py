"""Shared task wrapper for image-classification models (LeNet, ResNet).

Replaces the reference harness's per-model ``train_step`` bodies: softmax
cross-entropy (+ label smoothing / weight decay where the config says so),
accuracy metric, and the mutable ``batch_stats`` plumbing for BatchNorm
models.  Under global-array SPMD the BN statistics are computed over the
*global* batch (XLA inserts the cross-replica reductions), i.e. sync-BN
semantics — strictly stronger than the reference's default per-replica BN
(``tf_keras`` BatchNormalization under MirroredStrategy).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.ops.losses import (
    fold_sample_weight, softmax_cross_entropy,
)


class VisionTask:
    def __init__(self, model, *, label_smoothing: float = 0.0,
                 weight_decay: float = 0.0):
        self.model = model
        self.label_smoothing = label_smoothing
        self.weight_decay = weight_decay

    def init_variables(self, rng, batch):
        return self.model.init(rng, batch["image"], train=False)

    def loss_fn(self, params, model_state, batch, rng, train):
        variables = {"params": params, **model_state}
        if train and model_state:
            logits, updates = self.model.apply(
                variables, batch["image"], train=True,
                mutable=list(model_state.keys()),
            )
            new_model_state = updates
        else:
            logits = self.model.apply(variables, batch["image"], train=train)
            new_model_state = model_state
        # Per-example weights (the padded-final-batch eval contract,
        # data.pipeline drop_remainder=False): pad rows carry weight 0 so
        # a finite split's metrics are exact.
        weights = fold_sample_weight(batch, batch["label"].shape)
        loss, acc = softmax_cross_entropy(
            logits, batch["label"], label_smoothing=self.label_smoothing,
            weights=weights)
        metrics = {"accuracy": acc}
        if logits.shape[-1] > 5:
            # Top-5 — the ImageNet convention's second headline number
            # (only meaningful with more than 5 classes).
            top5 = jax.lax.top_k(logits.astype(jnp.float32), 5)[1]
            hit5 = (top5 == batch["label"][:, None]).any(-1)
            if weights is None:
                metrics["top5_accuracy"] = hit5.mean()
            else:
                metrics["top5_accuracy"] = (
                    (hit5 * weights).sum()
                    / jnp.maximum(weights.sum(), 1.0))
        if weights is not None:
            # Task contract: weighted losses report total weight so batch
            # metrics combine as the true weighted mean.
            metrics["loss_weight"] = weights.sum()
        if self.weight_decay > 0:
            # L2 on kernels only (reference ResNet convention: no decay on
            # BN scales/biases).
            l2 = sum(
                jnp.sum(jnp.square(p))
                for path, p in jax.tree_util.tree_leaves_with_path(params)
                if path[-1].key == "kernel"
            )
            loss = loss + self.weight_decay * l2
        return loss, (metrics, new_model_state)

    def predict_fn(self, params, model_state, batch):
        """Inference logits (Trainer.predict contract)."""
        return self.model.apply({"params": params, **model_state},
                                batch["image"], train=False)
