"""Shared task wrapper for image-classification models (LeNet, ResNet).

Replaces the reference harness's per-model ``train_step`` bodies: softmax
cross-entropy (+ label smoothing / weight decay where the config says so),
accuracy metric, and the mutable ``batch_stats`` plumbing for BatchNorm
models.  Under global-array SPMD the BN statistics are computed over the
*global* batch (XLA inserts the cross-replica reductions), i.e. sync-BN
semantics — strictly stronger than the reference's default per-replica BN
(``tf_keras`` BatchNormalization under MirroredStrategy).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.ops.losses import (
    fold_sample_weight, softmax_cross_entropy,
)


class VisionTask:
    def __init__(self, model, *, label_smoothing: float = 0.0,
                 weight_decay: float = 0.0,
                 uint8_mean_std=None):
        self.model = model
        self.label_smoothing = label_smoothing
        self.weight_decay = weight_decay
        # (mean, std) per channel in 0..255 pixel units: enables the
        # ship-raw-uint8 input contract (imagenet_*_u8_* transforms) —
        # hosts send raw bytes, normalization happens on device.
        self.uint8_mean_std = uint8_mean_std

    def _prep_image(self, image, params):
        """Device-side normalization for uint8 image batches.

        The ship-raw transforms move 4x less host→device data and skip
        host f32 math; here the raw pixels normalize in f32 and then
        JOIN THE POLICY COMPUTE DTYPE — taken from the already-cast
        params — so a bfloat16 policy keeps bf16 convs (an f32
        activations path would silently promote every conv to f32).
        0..255 and the affine are exact in f32, so this is bit-identical
        to host-side normalization followed by the policy cast.
        """
        if image.dtype != jnp.uint8:
            return image
        if self.uint8_mean_std is None:
            raise ValueError(
                "this task received a uint8 image batch but has no "
                "uint8_mean_std normalization constants; use a float "
                "transform (e.g. imagenet_train_224 / u8_image_to_f32) "
                "or construct the task with uint8_mean_std=(mean, std) "
                "in 0..255 pixel units")
        mean, std = self.uint8_mean_std
        reps = image.shape[-1] // len(mean)  # host-s2d ships 4x3 channels
        mean = jnp.tile(jnp.asarray(mean, jnp.float32), reps)
        std = jnp.tile(jnp.asarray(std, jnp.float32), reps)
        x = (image.astype(jnp.float32) - mean) / std
        leaves = jax.tree.leaves(params)
        return x.astype(leaves[0].dtype) if leaves else x

    def init_variables(self, rng, batch):
        return self.model.init(rng, self._prep_image(batch["image"], {}),
                               train=False)

    def loss_fn(self, params, model_state, batch, rng, train):
        variables = {"params": params, **model_state}
        image = self._prep_image(batch["image"], params)
        # Dropout-bearing models (ViT) consume the step rng; BN models
        # (ResNet/LeNet) have no 'dropout' rng collection and flax
        # ignores the extra entry.
        rngs = {"dropout": rng} if (train and rng is not None) else {}
        if train and model_state:
            logits, updates = self.model.apply(
                variables, image, train=True,
                mutable=list(model_state.keys()), rngs=rngs,
            )
            new_model_state = updates
        else:
            logits = self.model.apply(variables, image, train=train,
                                      rngs=rngs)
            new_model_state = model_state
        # Per-example weights (the padded-final-batch eval contract,
        # data.pipeline drop_remainder=False): pad rows carry weight 0 so
        # a finite split's metrics are exact.
        weights = fold_sample_weight(batch, batch["label"].shape)
        loss, acc = softmax_cross_entropy(
            logits, batch["label"], label_smoothing=self.label_smoothing,
            weights=weights)
        metrics = {"accuracy": acc}
        if logits.shape[-1] > 5:
            # Top-5 — the ImageNet convention's second headline number
            # (only meaningful with more than 5 classes).
            top5 = jax.lax.top_k(logits.astype(jnp.float32), 5)[1]
            hit5 = (top5 == batch["label"][:, None]).any(-1)
            if weights is None:
                metrics["top5_accuracy"] = hit5.mean()
            else:
                metrics["top5_accuracy"] = (
                    (hit5 * weights).sum()
                    / jnp.maximum(weights.sum(), 1.0))
        if weights is not None:
            # Task contract: weighted losses report total weight so batch
            # metrics combine as the true weighted mean.
            metrics["loss_weight"] = weights.sum()
        if self.weight_decay > 0:
            # L2 on kernels only (reference ResNet convention: no decay on
            # BN scales/biases).
            l2 = sum(
                jnp.sum(jnp.square(p))
                for path, p in jax.tree_util.tree_leaves_with_path(params)
                if path[-1].key == "kernel"
            )
            loss = loss + self.weight_decay * l2
        return loss, (metrics, new_model_state)

    def predict_fn(self, params, model_state, batch):
        """Inference logits (Trainer.predict contract)."""
        return self.model.apply({"params": params, **model_state},
                                self._prep_image(batch["image"], params),
                                train=False)
