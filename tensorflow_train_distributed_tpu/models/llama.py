"""Llama-2 decoder for SFT — reference config[4] (DTensor 2-D mesh stretch).

The reference's stretch goal shards Llama-2-7B over a data×model DTensor
mesh (``dtensor/python/layout.py``).  Here the same 2-D (or 3-D, with seq)
layout is just the rules table: embed/mlp/heads on ``tensor``, batch on
``data``/``fsdp``, length on ``seq`` — one model definition covers dp_tp,
fsdp_tp and dp_tp_sp presets.

TPU-first scale choices:
- ``scan_layers``: one compiled block scanned over the depth axis — compile
  time stays O(1) in layers (32 layers of 7B would otherwise take minutes).
- ``remat``: per-block rematerialization trades FLOPs for HBM, the standard
  recipe for 7B on small chips.
- attention runs the pallas flash kernel on TPU (``ops.attention``).

Architecture per Llama-2: RMSNorm pre-norm, RoPE, SwiGLU FFN, untied LM
head, optional GQA (num_kv_heads < num_heads).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.runtime import compat
from tensorflow_train_distributed_tpu.models import layers as L
from tensorflow_train_distributed_tpu.ops.losses import (
    fold_sample_weight, softmax_cross_entropy,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    d_model: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None → MHA (llama-2-7b)
    ffn_size: int = 11_008
    max_positions: int = 4096
    rope_base: float = 10_000.0
    rms_epsilon: float = 1e-5
    dtype: object = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = True
    # What remat saves (only meaningful with remat=True):
    #   "full" — save only layer boundaries, recompute everything (max
    #            memory savings, ~1.3x recompute FLOPs; the 7B default);
    #   "dots" — save matmul/einsum outputs, recompute elementwise chains
    #            (jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    #            — the standard LLM policy: most of full-remat's memory win
    #            at a fraction of the recompute, so higher MFU when HBM
    #            allows; attention internals still stream via the flash
    #            kernel, which saves only q/k/v + LSE regardless);
    #   "no_ffn" — save everything EXCEPT the [B,S,ffn] SwiGLU hiddens
    #            (the dominant no-remat buffers): backward re-runs only
    #            the two FFN input matmuls + activation — near-no-remat
    #            speed at a fraction of its memory.
    remat_policy: str = "full"
    # "ring" | "ulysses" | None — context parallelism over the seq mesh axis.
    seq_parallel: object = None
    # Sliding-window causal attention (Mistral-7B convention): each token
    # attends to the last ``sliding_window`` positions including itself.
    # Long sequences take the O(S·window) chunked attention path — the
    # long-context lever when full attention's S² won't fit; None = full
    # causal attention.  Composes with ring/Ulysses seq_parallel (the
    # ring skips out-of-window hops) and with packing.
    sliding_window: Optional[int] = None
    # StreamingLLM attention sinks (needs sliding_window): the first N
    # positions stay attendable past the window; decode keeps them in a
    # small buffer beside the rolling KV ring, so unbounded streaming
    # generation stays stable.  Composes with ring AND Ulysses SP.
    attention_sinks: int = 0
    # GPipe microbatch count: when set AND the ambient mesh has a
    # ``pipeline`` axis > 1, the depth scan is replaced by the
    # ``parallel.pipeline`` schedule (each stage holds a contiguous layer
    # group; same stacked params, same math, pipelined execution).  The
    # schedule needs scan_layers (the stacked-parameter layout).
    pipeline_microbatches: Optional[int] = None
    # LoRA fine-tuning (models.lora.LoraSpec): frozen base + trainable
    # low-rank adapters on the targeted projections.  The task applies
    # the model under lora_scope; pair the optimizer with
    # lora.freeze_base.  None = full fine-tuning.
    lora: object = None
    # int8 KV cache for decode (linear cache only): halves cache HBM
    # traffic/footprint — the large-batch/long-context serving lever;
    # per-(position, kv_head) scales, dequant fused into the attention
    # read.  Training is unaffected (no cache there).
    kv_cache_int8: bool = False
    # One fused qkv gemm instead of three (layers.MultiHeadAttention
    # fused_qkv): an MFU lever for small decoders where three
    # launch-bound projections under-fill the MXU.  The param tree
    # differs from the split layout — pick before training; single-chip
    # / dp meshes (the fused-dim slices fight a tensor axis).
    fused_qkv: bool = False
    # q/k/v projection biases, out-proj unbiased (the Qwen2/Qwen2.5
    # dense-family convention — layers.MultiHeadAttention.qkv_bias);
    # Llama/Mistral stay bias-free.
    qkv_bias: bool = False
    # Gemma-family knobs.  head_dim decouples the attention width from
    # d_model/num_heads (gemma-2b: d=2048, 8 heads, head_dim 256);
    # None = the Llama derivation.  embed_scale multiplies token
    # embeddings by sqrt(d_model) at input.  mlp_activation "gelu"
    # makes the gated MLP GeGLU (tanh-approx, HF gelu_pytorch_tanh);
    # "silu" is SwiGLU.  norm_zero_centered stores RMSNorm scales as
    # deviations from identity (output x̂·(1+w)) so Gemma checkpoints
    # map verbatim.
    head_dim: Optional[int] = None
    embed_scale: bool = False
    mlp_activation: str = "silu"
    norm_zero_centered: bool = False
    # Llama-3.x frequency-dependent RoPE scaling: (factor,
    # low_freq_factor, high_freq_factor, original_max_positions) —
    # layers.llama3_scaled_freqs; None = plain RoPE.  A tuple (not a
    # dict) so the frozen config stays hashable for jit static args.
    rope_scaling: Optional[tuple] = None

    def __post_init__(self):
        if self.mlp_activation not in ("silu", "gelu"):
            # Config-time, not a KeyError deep inside the first trace.
            raise ValueError(
                f"mlp_activation must be 'silu' (SwiGLU) or 'gelu' "
                f"(GeGLU, tanh approximation), got "
                f"{self.mlp_activation!r}")
        if self.fused_qkv and self.lora is not None:
            attn = ({"query", "key", "value"}
                    & set(getattr(self.lora, "targets", ())))
            if attn:
                # The q/k/v Dense modules become one "qkv" module, so
                # name-based LoRA targeting of them matches NOTHING —
                # and if any non-attention target still matches, the
                # n_lora==0 structural guard passes and a frozen-base
                # run silently trains without attention adapters.
                raise ValueError(
                    f"fused_qkv replaces the q/k/v projections with one "
                    f"'qkv' module; LoRA targets {sorted(attn)} would "
                    "match nothing — fine-tune attention with "
                    "fused_qkv=False")


LLAMA_PRESETS = {
    "llama2_7b": LlamaConfig(),
    # Mistral-7B shape: GQA(8) + sliding-window 4096 over 32k positions —
    # the long-context config where chunked local attention replaces the
    # S² score matrix.
    "mistral_7b": LlamaConfig(num_kv_heads=8, ffn_size=14_336,
                              max_positions=32_768, rope_base=1e6,
                              sliding_window=4096),
    # Qwen2.5-7B shape (qkv-bias convention; --init-from-hf a local
    # checkpoint imports it exactly).
    "qwen25_7b": LlamaConfig(vocab_size=152_064, d_model=3584,
                             num_layers=28, num_heads=28,
                             num_kv_heads=4, ffn_size=18_944,
                             max_positions=32_768, rope_base=1e6,
                             rms_epsilon=1e-6, qkv_bias=True),
    # Gemma-1 shapes: decoupled 256-wide heads, sqrt(d) embed scale,
    # GeGLU, zero-centered norms, tied embeddings (import maps the tied
    # head automatically).  2b is MQA (kv=1).
    "gemma_2b": LlamaConfig(vocab_size=256_000, d_model=2048,
                            num_layers=18, num_heads=8, num_kv_heads=1,
                            head_dim=256, ffn_size=16_384,
                            max_positions=8192, rms_epsilon=1e-6,
                            embed_scale=True, mlp_activation="gelu",
                            norm_zero_centered=True),
    "gemma_7b": LlamaConfig(vocab_size=256_000, d_model=3072,
                            num_layers=28, num_heads=16,
                            num_kv_heads=16, head_dim=256,
                            ffn_size=24_576, max_positions=8192,
                            rms_epsilon=1e-6, embed_scale=True,
                            mlp_activation="gelu",
                            norm_zero_centered=True),
    # Llama-3.1-8B shape: GQA(8), 128k vocab, 500k rope base with the
    # llama3 frequency-scaling tuple (factor 8, low 1, high 4, original
    # context 8192) — --init-from-hf maps checkpoints exactly.
    "llama31_8b": LlamaConfig(vocab_size=128_256, num_layers=32,
                              num_heads=32, num_kv_heads=8,
                              ffn_size=14_336, max_positions=131_072,
                              rope_base=500_000.0,
                              rope_scaling=(8.0, 1.0, 4.0, 8192)),
    "llama2_13b": LlamaConfig(d_model=5120, num_layers=40, num_heads=40,
                              ffn_size=13_824),
    "llama_1b": LlamaConfig(d_model=2048, num_layers=16, num_heads=16,
                            ffn_size=5504),
    # ~350M-param GPT-medium-class decoder: the mid-size MFU point — big
    # enough that matmuls dominate per-op overheads (the measured 125m
    # ceiling), small enough to train on one 16 GiB chip with no_ffn.
    "llama_350m": LlamaConfig(d_model=1024, num_layers=24, num_heads=16,
                              ffn_size=2816, max_positions=2048),
    # ~125M-param GPT-2-small-class decoder: the flagship fwd path at a
    # size that compiles fast everywhere (same code path as llama2_7b;
    # also the __graft_entry__ flagship and the LM benchmark default).
    "llama_125m": LlamaConfig(d_model=768, num_layers=12, num_heads=12,
                              ffn_size=2048, max_positions=2048),
    "llama_tiny": LlamaConfig(vocab_size=256, d_model=64, num_layers=2,
                              num_heads=4, num_kv_heads=2, ffn_size=128,
                              max_positions=128, dtype=jnp.float32,
                              scan_layers=False, remat=False),
    "llama_tiny_scan": LlamaConfig(vocab_size=256, d_model=64, num_layers=2,
                                   num_heads=4, num_kv_heads=2, ffn_size=128,
                                   max_positions=128, dtype=jnp.float32,
                                   scan_layers=True, remat=True),
    # Pipeline-parallel CI variant: 4 layers so a 2-stage mesh holds 2
    # layers/stage, exercising the grouped gpipe schedule.
    "llama_tiny_pp": LlamaConfig(vocab_size=256, d_model=64, num_layers=4,
                                 num_heads=4, num_kv_heads=2, ffn_size=128,
                                 max_positions=128, dtype=jnp.float32,
                                 scan_layers=True, remat=True,
                                 pipeline_microbatches=4),
}


def _checkpoint_policy(cfg: LlamaConfig):
    """jax.checkpoint policy for the config's ``remat_policy`` name."""
    if cfg.remat_policy == "full":
        return None  # save nothing beyond layer boundaries
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    if cfg.remat_policy == "no_ffn":
        # "no_ffn" has NO outer block checkpoint (callers must not wrap;
        # gate on wants_outer_remat below).  The exclusion of the [B,S,ffn] SwiGLU
        # hiddens — the buffers that dominate the no-remat footprint
        # (PROFILE.md) — is STRUCTURAL: DecoderBlock wraps the MlpBlock
        # in an inner nothing-saveable nn.remat, and everything outside
        # it is saved scan-normally.  Two approaches that do NOT work,
        # both verified empirically: (a) save_anything_except_these_names
        # leaves the pre-tag producer values saveable (6 stacked
        # [L,B,S,ffn] buffers in the v5e OOM dump); (b) an outer
        # everything_saveable checkpoint DISSOLVES inner nothing-saveable
        # regions (their internals become the outer's residuals).
        raise AssertionError(
            "no_ffn takes no outer checkpoint; gate on wants_outer_remat")
    raise ValueError(
        f"Unknown remat_policy {cfg.remat_policy!r}; expected 'full', "
        "'dots' or 'no_ffn'")


def wants_outer_remat(cfg: LlamaConfig) -> bool:
    """Whether the per-block (outer) nn.remat wrap applies.  False for
    remat=False and for the "no_ffn" policy, whose only checkpoint is the
    inner FFN region (an outer wrap would either re-introduce full
    recompute or dissolve the inner region — see _checkpoint_policy)."""
    return cfg.remat and cfg.remat_policy != "no_ffn"


class DecoderBlock(nn.Module):
    config: LlamaConfig
    decode: bool = False
    cache_len: int = 0
    slot_decode: bool = False
    # Paged serving KV cache (serving.ServingEngine paged mode) — see
    # layers.MultiHeadAttention.paged_kv_blocks.
    paged_kv_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, x, segment_ids=None, positions=None):
        cfg = self.config
        h = L.RMSNorm(epsilon=cfg.rms_epsilon, dtype=cfg.dtype,
                      zero_centered=cfg.norm_zero_centered,
                      name="attn_norm")(x)
        x = x + L.MultiHeadAttention(
            num_heads=cfg.num_heads,
            head_dim=cfg.head_dim or cfg.d_model // cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            dtype=cfg.dtype, causal=True, use_rope=True,
            rope_base=cfg.rope_base, rope_scaling=cfg.rope_scaling,
            seq_parallel=cfg.seq_parallel,
            window=cfg.sliding_window, sinks=cfg.attention_sinks,
            decode=self.decode,
            cache_len=self.cache_len or cfg.max_positions,
            kv_cache_int8=cfg.kv_cache_int8,
            slot_decode=self.slot_decode,
            paged_kv_blocks=self.paged_kv_blocks,
            kv_block_size=self.kv_block_size,
            fused_qkv=cfg.fused_qkv,
            qkv_bias=cfg.qkv_bias,
            name="attention",
        )(h, segment_ids=segment_ids, positions=positions)
        h = L.RMSNorm(epsilon=cfg.rms_epsilon, dtype=cfg.dtype,
                      zero_centered=cfg.norm_zero_centered,
                      name="mlp_norm")(x)
        mlp_cls = L.MlpBlock
        if cfg.remat and cfg.remat_policy == "no_ffn" and not self.decode:
            # "no_ffn": the FFN runs inside an inner nothing-saveable
            # remat region, so no [B,S,ffn] intermediate can be saved —
            # backward re-runs the FFN from its (saved) input.  nn.remat
            # on the module class is param-path-transparent, so
            # checkpoints load unchanged.  The outer block policy is
            # everything_saveable (see _checkpoint_policy): name-based
            # exclusion does NOT drop the hiddens (the pre-tag producer
            # value stays saveable — verified in a v5e OOM dump).
            mlp_cls = nn.remat(
                L.MlpBlock, prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable)
        x = x + mlp_cls(
            hidden=cfg.ffn_size, dtype=cfg.dtype,
            activation={"silu": nn.silu, "gelu": nn.gelu}[
                cfg.mlp_activation],
            gated=True, name="mlp")(h)
        return x


def segment_relative_positions(segment_ids: jax.Array) -> jax.Array:
    """[B, S] segment ids → [B, S] positions restarting at each segment.

    Positions are what RoPE sees: in a packed row each document must be
    encoded at 0..len-1, not at its offset in the row.  Padding (its own
    segment id) restarts too — harmless, those positions are loss-masked.
    """
    s = segment_ids.shape[-1]
    idx = jnp.arange(s)
    restart = jnp.concatenate(
        [jnp.ones_like(segment_ids[..., :1], bool),
         segment_ids[..., 1:] != segment_ids[..., :-1]], axis=-1)
    last_restart = jax.lax.associative_scan(
        jnp.maximum, jnp.where(restart, idx, 0), axis=-1)
    return idx - last_restart


class _BlockStep(nn.Module):
    """scan-compatible adapter: (carry, aux) → (carry, None); ``aux`` is
    the nn.broadcast (segment_ids, positions) pair shared by all layers."""

    config: LlamaConfig
    decode: bool = False
    cache_len: int = 0
    slot_decode: bool = False
    paged_kv_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, carry, aux):
        segment_ids, positions = aux if aux is not None else (None, None)
        return DecoderBlock(self.config, decode=self.decode,
                            cache_len=self.cache_len,
                            slot_decode=self.slot_decode,
                            paged_kv_blocks=self.paged_kv_blocks,
                            kv_block_size=self.kv_block_size,
                            name="block")(carry, segment_ids,
                                          positions), None


class _ScannedBlock(nn.Module):
    """Depth-scanned stack: params get a leading ``stage`` axis, so compile
    time is O(1) in depth and the pipeline axis can shard layers."""

    config: LlamaConfig
    decode: bool = False
    cache_len: int = 0
    slot_decode: bool = False
    paged_kv_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, x, segment_ids=None, positions=None):
        from functools import partial as _partial

        # slot_decode (and the paged-pool knobs) thread through BOTH
        # branches so the layer guards ("slot_decode requires
        # decode=True", ditto paged_kv_blocks) fire under scan_layers
        # exactly as they do on the unscanned path.
        step = (_partial(_BlockStep, decode=True,
                         cache_len=self.cache_len,
                         slot_decode=self.slot_decode,
                         paged_kv_blocks=self.paged_kv_blocks,
                         kv_block_size=self.kv_block_size)
                if self.decode
                else _partial(_BlockStep,
                              slot_decode=self.slot_decode,
                              paged_kv_blocks=self.paged_kv_blocks,
                              kv_block_size=self.kv_block_size))
        # No remat in decode mode: there is no backward pass to save memory
        # for, and the KV-cache writes must not replay under a checkpoint.
        if wants_outer_remat(self.config) and not self.decode:
            step = nn.remat(step, prevent_cse=False,
                            policy=_checkpoint_policy(self.config))
        scanned = nn.scan(
            step,
            # "quant": stacked int8 serving scales (models.quant) slice
            # per-layer exactly like the stacked params they mirror;
            # absent collections are ignored by nn.scan.
            variable_axes={"params": 0, "cache": 0, "quant": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,  # (segment_ids, positions): all layers
            length=self.config.num_layers,
            metadata_params={nn.PARTITION_NAME: "stage"},
        )
        x, _ = scanned(self.config, name="stack")(
            x, (segment_ids, positions))
        return x


def _pipeline_mesh(cfg: LlamaConfig):
    """The ambient mesh when the gpipe path is requested and usable."""
    if not (cfg.pipeline_microbatches and cfg.scan_layers):
        return None
    mesh = compat.get_abstract_mesh()
    if mesh is None or mesh.empty or mesh.shape.get("pipeline", 1) <= 1:
        return None
    return mesh


def _pipelined_blocks(cfg: LlamaConfig, block_params, x, mesh,
                      segment_ids=None, positions=None):
    """Decoder stack as a GPipe schedule over the ``pipeline`` mesh axis.

    ``block_params`` is the nn.scan-stacked DecoderBlock tree (leading dim
    ``num_layers``, sharded over ``pipeline`` by the ``stage`` rule) — the
    SAME parameters the depth scan uses, so dp and dp_pp runs of one
    checkpoint are numerically identical.

    Packed rows: segment ids / positions ride the pipeline carry WITH the
    activation — at tick t, stage s is processing microbatch t−s, so
    side inputs cannot be indexed by tick at later stages; shipping them
    through the same ppermute hop keeps each microbatch's metadata
    aligned with its activation (int [mb,S] hops are <1% of the [mb,S,D]
    activation bytes at real widths).
    """
    from tensorflow_train_distributed_tpu.parallel.pipeline import (
        gpipe_layers,
    )

    # Constructed OUTSIDE layer_fn: flax forbids Module CONSTRUCTION at
    # a deeper trace level than the enclosing module context (layer_fn
    # runs inside scan-in-shard_map), while ``.apply`` opens a fresh
    # context and is legal anywhere.
    block = DecoderBlock(cfg)

    def layer_fn(p, carry):
        h, seg, pos = carry
        # Inside shard_map every mesh axis is manual: logical sharding
        # constraints are meaningless there (and illegal to apply), so the
        # block runs under empty rules — pure per-shard compute.
        with nn.logical_axis_rules(()):
            h = block.apply({"params": p}, h, seg, pos)
        return (h, seg, pos)

    if wants_outer_remat(cfg):
        layer_fn = jax.checkpoint(layer_fn, prevent_cse=False,
                                  policy=_checkpoint_policy(cfg))
    data_axes = tuple(a for a in ("data", "fsdp")
                      if mesh.shape.get(a, 1) > 1)
    out, _, _ = gpipe_layers(
        layer_fn, block_params, (x, segment_ids, positions), mesh=mesh,
        num_microbatches=cfg.pipeline_microbatches,
        batch_axes=data_axes,
    )
    return out


class LlamaModel(nn.Module):
    # ``decode=True``: autoregressive KV-cache mode (models.generate) —
    # same params, plus a mutable "cache" collection sized max_positions.
    config: LlamaConfig = LlamaConfig()
    decode: bool = False
    # Decode-mode KV cache size; 0 → config.max_positions.  generate()
    # passes the statically-known prompt_len + max_new_tokens so short
    # generations from a long-context config don't allocate (and attend
    # over) the full max_positions cache.
    cache_len: int = 0
    # Per-slot cache positions (continuous-batching serving,
    # serving.ServingEngine): the cache "index" is a [B] vector, one position
    # per slot.  Linear full-precision cache only — see
    # layers.MultiHeadAttention.slot_decode.
    slot_decode: bool = False
    # Paged serving KV cache: >0 turns the per-lane contiguous cache
    # into a fixed physical block pool + per-lane block table — see
    # layers.MultiHeadAttention.paged_kv_blocks.
    paged_kv_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, tokens, *, segment_ids=None, positions=None):
        cfg = self.config
        if segment_ids is not None and self.decode:
            raise ValueError("decode mode does not take packed segments")
        if segment_ids is not None and positions is None:
            # Packed rows: RoPE positions restart at each segment
            # boundary (each document sees itself at positions 0..len-1,
            # exactly as if it were alone in the row).
            positions = segment_relative_positions(segment_ids)
        x = L.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                    name="token_embed")(tokens)
        if cfg.embed_scale:
            # Gemma input normalizer; the cast mirrors HF (the constant
            # is materialized in the activation dtype).
            x = x * jnp.asarray(
                cfg.d_model ** 0.5, x.dtype)
        pp_mesh = None if self.is_initializing() else _pipeline_mesh(cfg)
        if pp_mesh is not None and self.decode:
            raise ValueError(
                "decode mode does not run under a pipeline mesh; generate "
                "outside the pipeline strategy")
        if pp_mesh is not None:
            # Params were created by the scan path (init always takes it);
            # read the stacked block tree and drive the pipeline schedule.
            # Packed segment ids / positions ride the pipeline carry.
            block_params = (
                self.variables["params"]["layers"]["stack"]["block"])
            x = _pipelined_blocks(cfg, block_params, x, pp_mesh,
                                  segment_ids, positions)
        elif cfg.scan_layers:
            x = _ScannedBlock(cfg, decode=self.decode,
                              cache_len=self.cache_len,
                              slot_decode=self.slot_decode,
                              paged_kv_blocks=self.paged_kv_blocks,
                              kv_block_size=self.kv_block_size,
                              name="layers")(
                x, segment_ids, positions)
        else:
            for i in range(cfg.num_layers):
                blk = DecoderBlock
                if wants_outer_remat(cfg) and not self.decode:
                    blk = nn.remat(blk, prevent_cse=False,
                                   policy=_checkpoint_policy(cfg))
                x = blk(cfg, decode=self.decode,
                        cache_len=self.cache_len,
                        slot_decode=self.slot_decode,
                        paged_kv_blocks=self.paged_kv_blocks,
                        kv_block_size=self.kv_block_size,
                        name=f"layer_{i}")(
                    x, segment_ids, positions)
        x = L.RMSNorm(epsilon=cfg.rms_epsilon, dtype=cfg.dtype,
                      zero_centered=cfg.norm_zero_centered,
                      name="final_norm")(x)
        logits = L.dense(cfg.vocab_size, ("embed", "vocab"), use_bias=False,
                         dtype=cfg.dtype, name="lm_head")(x)
        return nn.with_logical_constraint(
            logits, ("batch", "length", "vocab"))


class CausalLmTask:
    """Next-token objective over ``SyntheticLM`` batches (SFT-shaped)."""

    report_perplexity = True  # evaluate() adds exp(mean loss)

    def __init__(self, config: LlamaConfig = LlamaConfig()):
        self.config = config
        self.model = LlamaModel(config)

    def _scope(self):
        """LoRA interception context when the config asks for it."""
        from tensorflow_train_distributed_tpu.models.lora import (
            maybe_lora_scope,
        )

        return maybe_lora_scope(self.config.lora)

    def init_variables(self, rng, batch):
        with self._scope():
            variables = self.model.init(rng, batch["tokens"])
        if self.config.lora is not None:
            # Structural check at the right altitude: a target list that
            # matches no module (beyond what name validation can know)
            # would freeze everything and silently train nothing.
            from tensorflow_train_distributed_tpu.models.lora import (
                count_lora_params,
            )

            n_lora, _ = count_lora_params(variables["params"])
            if n_lora == 0:
                raise ValueError(
                    f"LoRA targets {self.config.lora.targets} matched no "
                    "module in this model — no adapters were created, so "
                    "a frozen-base run would train nothing")
        return variables

    def loss_fn(self, params, model_state, batch, rng, train):
        del rng, train  # no dropout in llama pretraining/SFT
        with self._scope():
            logits = self.model.apply(
                {"params": params}, batch["tokens"],
                segment_ids=batch.get("segment_ids")).astype(jnp.float32)
        weights = fold_sample_weight(batch, batch["targets"].shape,
                                     batch.get("loss_weights"))
        loss, acc = softmax_cross_entropy(logits, batch["targets"],
                                          weights=weights)
        metrics = {"accuracy": acc}
        if weights is not None:
            # Grad-accum recombination contract (Task docstring): weighted
            # losses report their total weight, unclamped per
            # fold_sample_weight's contract.
            metrics["loss_weight"] = weights.sum()
        return loss, (metrics, model_state)

    def predict_fn(self, params, model_state, batch):
        """Next-token logits (Trainer.predict contract)."""
        del model_state
        with self._scope():
            return self.model.apply({"params": params}, batch["tokens"],
                                    segment_ids=batch.get("segment_ids"))


def make_task(config: LlamaConfig = LLAMA_PRESETS["llama2_7b"]
              ) -> CausalLmTask:
    return CausalLmTask(config)
