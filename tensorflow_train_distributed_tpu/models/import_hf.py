"""Import HuggingFace Llama-family checkpoints into the native model.

Migration path for the reference's SFT config (SURVEY.md §2.1 config[4]:
"Llama-2-7B SFT"): users arrive with HF ``LlamaForCausalLM`` weights; this
maps them onto ``models.llama.LlamaModel``'s parameter tree so fine-tuning
continues here with TP/SP/FSDP shardings instead of the reference's DTensor
mesh.

Conventions that make the mapping exact (verified by the forward-parity
test against the torch implementation, tests/test_import_hf.py):

- torch ``nn.Linear`` stores ``[out, in]``; flax kernels are ``[in, out]``
  → every projection transposes.
- RoPE: both use the split-half ("rotate_half") pairing with
  ``inv_freq = base^(-2i/d)`` — q/k copy over with no permutation.
- RMSNorm epsilon/scale and the SwiGLU gate/up/down order match 1:1.

Only the Llama family is importable: our BERT encoder deliberately omits
token-type embeddings and q/k/v biases (TPU-first simplifications), so an
HF BERT checkpoint cannot be represented exactly — rejected with an error
rather than imported approximately.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from tensorflow_train_distributed_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config) -> LlamaConfig:
    """Derive a native ``LlamaConfig`` from a HF ``LlamaConfig``."""
    if getattr(hf_config, "model_type", "llama") not in ("llama", "mistral"):
        raise ValueError(
            f"import_hf supports Llama-family checkpoints, got model_type="
            f"{hf_config.model_type!r} (BERT-style models are not exactly "
            "representable here — see module docstring)")
    # Exact-or-rejected: attention-affecting options the native model does
    # not implement must fail loudly, not import into silently-different
    # logits.
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError(
            "checkpoint uses rope_scaling (Llama-3-style scaled RoPE), "
            "which the native model does not implement — importing would "
            "silently change logits at every position")
    if getattr(hf_config, "sliding_window", None):
        raise ValueError(
            "checkpoint uses sliding-window attention; the native model "
            "attends globally — not exactly representable")
    if getattr(hf_config, "attention_bias", False):
        raise ValueError(
            "checkpoint has q/k/v/o projection biases; the native "
            "attention is bias-free — not exactly representable")
    kv = getattr(hf_config, "num_key_value_heads",
                 hf_config.num_attention_heads)
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=None if kv == hf_config.num_attention_heads else kv,
        ffn_size=hf_config.intermediate_size,
        max_positions=hf_config.max_position_embeddings,
        rope_base=getattr(hf_config, "rope_theta", 10_000.0),
        rms_epsilon=hf_config.rms_norm_eps,
    )


def _np(t) -> np.ndarray:
    """torch tensor / array-like → float32 numpy (params live in f32)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _layer_tree(sd, i: int) -> dict:
    """One decoder layer's flax param tree from an HF state dict."""
    p = f"model.layers.{i}."
    return {
        "attn_norm": {"scale": _np(sd[p + "input_layernorm.weight"])},
        "attention": {
            "query": {"kernel": _np(sd[p + "self_attn.q_proj.weight"]).T},
            "key": {"kernel": _np(sd[p + "self_attn.k_proj.weight"]).T},
            "value": {"kernel": _np(sd[p + "self_attn.v_proj.weight"]).T},
            "out": {"kernel": _np(sd[p + "self_attn.o_proj.weight"]).T},
        },
        "mlp_norm": {"scale": _np(sd[p + "post_attention_layernorm.weight"])},
        "mlp": {
            "wi_gate": {"kernel": _np(sd[p + "mlp.gate_proj.weight"]).T},
            "wi_up": {"kernel": _np(sd[p + "mlp.up_proj.weight"]).T},
            "wo": {"kernel": _np(sd[p + "mlp.down_proj.weight"]).T},
        },
    }


def import_llama_state_dict(state_dict, config: LlamaConfig) -> dict:
    """HF ``LlamaForCausalLM`` state dict → native flax ``params`` tree.

    Honors ``config.scan_layers`` (stacks per-layer trees along a leading
    axis, the nn.scan layout) vs per-layer ``layer_{i}`` modules.
    """
    sd = state_dict
    embed = _np(sd["model.embed_tokens.weight"])
    if embed.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"checkpoint embed is {embed.shape}, config expects "
            f"{(config.vocab_size, config.d_model)}")
    # Exact layer-count match: a deeper checkpoint must not be silently
    # truncated (training would proceed on a corrupted model), a shallower
    # one fails here instead of with an opaque KeyError mid-mapping.
    def _has_layer(i):
        return f"model.layers.{i}.input_layernorm.weight" in sd

    if _has_layer(config.num_layers) or not _has_layer(
            config.num_layers - 1):
        n = 0
        while _has_layer(n):
            n += 1
        raise ValueError(
            f"checkpoint has {n} decoder layers, config expects "
            f"{config.num_layers}")
    biases = [k for k in sd if k.endswith("proj.bias")]
    if biases:
        raise ValueError(
            f"checkpoint has projection biases ({biases[0]}, ...); the "
            "native attention/MLP are bias-free — not exactly "
            "representable")
    if "lm_head.weight" in sd:
        lm_head = _np(sd["lm_head.weight"]).T
    else:  # tied-embedding checkpoints omit the head
        lm_head = embed.T.copy()
    params = {
        "token_embed": {"embedding": embed},
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
        "lm_head": {"kernel": lm_head},
    }
    layers = [_layer_tree(sd, i) for i in range(config.num_layers)]
    if config.scan_layers:
        import jax

        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *layers)
        params["layers"] = {"stack": {"block": stacked}}
    else:
        for i, tree in enumerate(layers):
            params[f"layer_{i}"] = tree
    return params


def import_llama(model_or_path, config: Optional[LlamaConfig] = None,
                 **config_overrides):
    """(native_config, params) from an HF model instance or local path.

    ``config_overrides`` tweak the derived config (e.g. ``scan_layers=
    False``, ``seq_parallel="ring"``) — anything not changing parameter
    shapes is safe.
    """
    if isinstance(model_or_path, str):
        from transformers import LlamaForCausalLM

        model_or_path = LlamaForCausalLM.from_pretrained(model_or_path)
    if config is None:
        config = config_from_hf(model_or_path.config)
    if config_overrides:
        config = dataclasses.replace(config, **config_overrides)
    params = import_llama_state_dict(model_or_path.state_dict(), config)
    return config, params
