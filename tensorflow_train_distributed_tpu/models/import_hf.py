"""Import HuggingFace Llama-family checkpoints into the native model.

Migration path for the reference's SFT config (SURVEY.md §2.1 config[4]:
"Llama-2-7B SFT"): users arrive with HF ``LlamaForCausalLM`` (or
``MistralForCausalLM`` — GQA + sliding window map onto the native
``num_kv_heads``/``sliding_window``) weights; this maps them onto
``models.llama.LlamaModel``'s parameter tree so fine-tuning continues
here with TP/SP/FSDP shardings instead of the reference's DTensor mesh.

Conventions that make the mapping exact (verified by the forward-parity
test against the torch implementation, tests/test_import_hf.py):

- torch ``nn.Linear`` stores ``[out, in]``; flax kernels are ``[in, out]``
  → every projection transposes.
- RoPE: both use the split-half ("rotate_half") pairing with
  ``inv_freq = base^(-2i/d)`` — q/k copy over with no permutation.
- RMSNorm epsilon/scale and the SwiGLU gate/up/down order match 1:1.

Only the Llama family is importable: our BERT encoder deliberately omits
token-type embeddings and q/k/v biases (TPU-first simplifications), so an
HF BERT checkpoint cannot be represented exactly — rejected with an error
rather than imported approximately.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from tensorflow_train_distributed_tpu.models.llama import LlamaConfig


def _validate_hf_llama_family(hf_config) -> None:
    """Exact-or-rejected guards — run on EVERY import path, including
    the CLI's ``config=task_cfg`` route (which skips config
    derivation); the Mixtral/Qwen2-MoE importers follow the same
    rule."""
    if getattr(hf_config, "model_type", "llama") not in (
            "llama", "mistral", "qwen2", "gemma"):
        raise ValueError(
            f"import_hf supports Llama-family checkpoints (llama, "
            f"mistral, qwen2, gemma), got model_type="
            f"{hf_config.model_type!r} (gemma2/gemma3 add logit "
            "softcapping / alternating windows the native model does "
            "not implement; BERT-style models are not representable "
            "here — see module docstring)")
    # Attention-affecting options the native model does not implement
    # must fail loudly, not import into silently-different logits.
    rs = getattr(hf_config, "rope_scaling", None)
    if rs and rs.get("rope_type", rs.get("type")) != "llama3":
        raise ValueError(
            f"rope_scaling type {rs.get('rope_type', rs.get('type'))!r} "
            "is not implemented natively (only the llama3 "
            "frequency-dependent rule) — importing would silently "
            "change logits at every position")
    qwen2 = getattr(hf_config, "model_type", "") == "qwen2"
    if getattr(hf_config, "attention_bias", False) and not qwen2:
        raise ValueError(
            "checkpoint has q/k/v/o projection biases; the native "
            "attention is bias-free for this family — qwen2 (qkv-bias "
            "convention) imports via the same path, others are not "
            "exactly representable")
    gemma_family = getattr(hf_config, "model_type", "") == "gemma"
    if not gemma_family:
        # The native MLP for llama/mistral/qwen2 is SwiGLU (silu)
        # only; HF honors ACT2FN[hidden_act] as-is, so a checkpoint
        # carrying any other activation would import into
        # silently-different logits at every position (the same
        # exact-or-rejected rule the MoE importers apply).  Gemma's
        # activation convention is screened separately below.
        act = getattr(hf_config, "hidden_act", "silu") or "silu"
        if act != "silu":
            raise ValueError(
                f"hidden_act={act!r}: the native MLP for this family "
                "is SwiGLU (silu) only — importing would silently "
                "change every forward (Gemma's tanh-GeGLU is the one "
                "supported alternative, model_type='gemma')")
    if qwen2 and getattr(hf_config, "use_sliding_window", False):
        raise ValueError(
            "qwen2 use_sliding_window=True windows only layers past "
            "max_window_layers — a per-layer mix the native uniform "
            "window cannot represent; re-export the checkpoint with "
            "use_sliding_window=false (full attention)")
    gemma = getattr(hf_config, "model_type", "") == "gemma"
    hd = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    if hd and hd != derived and not gemma:
        raise ValueError(
            f"checkpoint uses an explicit head_dim={hd} != hidden_size/"
            f"num_heads ({hf_config.hidden_size}/"
            f"{hf_config.num_attention_heads}) — only the Gemma family "
            "imports with a decoupled head width "
            "(LlamaConfig.head_dim)")
    if gemma:
        # HF's GemmaMLP runs gelu_pytorch_tanh whenever
        # hidden_activation is None, IGNORING legacy hidden_act — so
        # original gemma configs (hidden_act="gelu", no
        # hidden_activation) map exactly onto the native tanh GeGLU
        # and import fine; only an EXPLICIT different hidden_activation
        # (exact erf gelu, honored by HF when set) is rejected.
        act = getattr(hf_config, "hidden_activation", None)
        if act is not None and act != "gelu_pytorch_tanh":
            raise ValueError(
                f"gemma hidden_activation={act!r} is honored by HF "
                "as-is; only 'gelu_pytorch_tanh' (or None, HF's "
                "default) maps exactly onto the native GeGLU")


def config_from_hf(hf_config) -> LlamaConfig:
    """Derive a native ``LlamaConfig`` from a HF ``LlamaConfig``."""
    _validate_hf_llama_family(hf_config)
    rs = getattr(hf_config, "rope_scaling", None)  # llama3-validated
    qwen2 = getattr(hf_config, "model_type", "") == "qwen2"
    gemma = getattr(hf_config, "model_type", "") == "gemma"
    hd = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    kv = getattr(hf_config, "num_key_value_heads",
                 hf_config.num_attention_heads)
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=None if kv == hf_config.num_attention_heads else kv,
        ffn_size=hf_config.intermediate_size,
        max_positions=hf_config.max_position_embeddings,
        rope_base=getattr(hf_config, "rope_theta", 10_000.0),
        rms_epsilon=hf_config.rms_norm_eps,
        # Mistral-family checkpoints: HF masks keys at distance >=
        # sliding_window — identical semantics to the native window
        # (last `window` keys including self), torch-parity-tested.
        # `or None`: a checkpoint carrying sliding_window=0 means
        # disabled, and must import as full attention, not crash at the
        # first forward (exact-or-rejected happens HERE).  Qwen2 ships
        # use_sliding_window=False with a non-null sliding_window field
        # — honor the switch; True is rejected above (HF windows only
        # layers past max_window_layers, a per-layer mix the native
        # uniform window cannot represent).
        sliding_window=(
            None if (qwen2 or gemma)
            else getattr(hf_config, "sliding_window", None) or None),
        qkv_bias=qwen2,
        rope_scaling=(
            (float(rs["factor"]), float(rs["low_freq_factor"]),
             float(rs["high_freq_factor"]),
             int(rs["original_max_position_embeddings"]))
            if rs else None),
        # Gemma conventions (all no-ops for the other families).
        head_dim=(hd if gemma and hd and hd != derived else None),
        embed_scale=gemma,
        mlp_activation="gelu" if gemma else "silu",
        norm_zero_centered=gemma,
    )


def _np(t) -> np.ndarray:
    """torch tensor / array-like → float32 numpy (params live in f32)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _layer_tree(sd, i: int, qkv_bias: bool = False) -> dict:
    """One decoder layer's flax param tree from an HF state dict."""
    p = f"model.layers.{i}."

    def proj(name):
        t = {"kernel": _np(sd[p + f"self_attn.{name}.weight"]).T}
        if qkv_bias:
            t["bias"] = _np(sd[p + f"self_attn.{name}.bias"])
        return t

    return {
        "attn_norm": {"scale": _np(sd[p + "input_layernorm.weight"])},
        "attention": {
            "query": proj("q_proj"),
            "key": proj("k_proj"),
            "value": proj("v_proj"),
            "out": {"kernel": _np(sd[p + "self_attn.o_proj.weight"]).T},
        },
        "mlp_norm": {"scale": _np(sd[p + "post_attention_layernorm.weight"])},
        "mlp": {
            "wi_gate": {"kernel": _np(sd[p + "mlp.gate_proj.weight"]).T},
            "wi_up": {"kernel": _np(sd[p + "mlp.up_proj.weight"]).T},
            "wo": {"kernel": _np(sd[p + "mlp.down_proj.weight"]).T},
        },
    }


def import_llama_state_dict(state_dict, config: LlamaConfig) -> dict:
    """HF ``LlamaForCausalLM`` state dict → native flax ``params`` tree.

    Honors ``config.scan_layers`` (stacks per-layer trees along a leading
    axis, the nn.scan layout) vs per-layer ``layer_{i}`` modules.
    """
    if getattr(config, "fused_qkv", False):
        raise ValueError(
            "fused_qkv configs use one 'qkv' kernel; HF checkpoints ship "
            "split q/k/v projections — import with fused_qkv=False (the "
            "layouts are not interchangeable)")
    sd = state_dict
    embed = _np(sd["model.embed_tokens.weight"])
    if embed.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"checkpoint embed is {embed.shape}, config expects "
            f"{(config.vocab_size, config.d_model)}")
    _probe_count(sd, "model.layers.{}.input_layernorm.weight",
                 config.num_layers, "decoder layers")
    allowed = (("q_proj.bias", "k_proj.bias", "v_proj.bias")
               if getattr(config, "qkv_bias", False) else ())
    biases = [k for k in sd
              if k.endswith("proj.bias") and not k.endswith(allowed)]
    if biases:
        raise ValueError(
            f"checkpoint has projection biases the config cannot "
            f"represent ({biases[0]}, ...); qkv_bias=True covers "
            "q/k/v biases only (the Qwen2 convention) — anything else "
            "would be silently dropped")
    if allowed and "model.layers.0.self_attn.q_proj.bias" not in sd:
        # The symmetric boundary check: a bias-free checkpoint (e.g.
        # plain Llama weights under the qwen25_7b preset) would
        # otherwise die with an opaque KeyError mid-mapping.
        raise ValueError(
            "config sets qkv_bias=True (the Qwen2 convention) but the "
            "checkpoint has no q/k/v projection biases "
            "(model.layers.0.self_attn.q_proj.bias is absent) — import "
            "with qkv_bias=False or use a matching config/preset")
    params = {
        "token_embed": {"embedding": embed},
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
        "lm_head": {"kernel": _lm_head_or_tied(sd, embed)},
    }
    layers = [_layer_tree(sd, i, getattr(config, 'qkv_bias', False))
              for i in range(config.num_layers)]
    if config.scan_layers:
        import jax

        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *layers)
        params["layers"] = {"stack": {"block": stacked}}
    else:
        for i, tree in enumerate(layers):
            params[f"layer_{i}"] = tree
    return params


def config_from_hf_bert(hf_config) -> "BertConfig":
    """Derive a native ``BertConfig`` (HF-compat knobs on) from an HF
    ``BertConfig``."""
    from tensorflow_train_distributed_tpu.models.bert import BertConfig

    if getattr(hf_config, "model_type", "bert") != "bert":
        raise ValueError(
            f"import_bert expects model_type 'bert', got "
            f"{hf_config.model_type!r}")
    if getattr(hf_config, "position_embedding_type", "absolute") != \
            "absolute":
        raise ValueError(
            "only absolute learned position embeddings are representable")
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported hidden_act {act!r}")
    return BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_positions=hf_config.max_position_embeddings,
        dropout_rate=hf_config.hidden_dropout_prob,
        attention_dropout_rate=hf_config.attention_probs_dropout_prob,
        attention_bias=True,
        type_vocab_size=hf_config.type_vocab_size,
        embed_layer_norm=True,
        layer_norm_eps=hf_config.layer_norm_eps,
        exact_gelu=(act == "gelu"),  # HF "gelu" = erf; *_new/_tanh ≈ tanh
    )


def _ln(sd, prefix):
    return {"scale": _np(sd[prefix + ".weight"]),
            "bias": _np(sd[prefix + ".bias"])}


def _dense(sd, prefix):
    return {"kernel": _np(sd[prefix + ".weight"]).T,
            "bias": _np(sd[prefix + ".bias"])}


def import_bert_state_dict(state_dict, config) -> dict:
    """HF ``BertForMaskedLM`` state dict → native flax ``params`` tree.

    Requires a config from ``config_from_hf_bert`` (HF-compat knobs on);
    the MLM head decoder must be tied to the word embeddings (the HF
    default) — its logits come from ``Embed.attend`` here.
    """
    sd = state_dict
    if not (config.attention_bias and config.embed_layer_norm
            and config.type_vocab_size):
        raise ValueError(
            "import_bert needs the HF-compat config knobs on "
            "(attention_bias, embed_layer_norm, type_vocab_size) — build "
            "the config with config_from_hf_bert()")
    emb = "bert.embeddings."
    params = {
        "token_embed": {
            "embedding": _np(sd[emb + "word_embeddings.weight"])},
        "pos_embedding": _np(sd[emb + "position_embeddings.weight"]),
        "type_embedding": _np(sd[emb + "token_type_embeddings.weight"]),
        "embed_ln": _ln(sd, emb + "LayerNorm"),
        "mlm_transform": _dense(sd, "cls.predictions.transform.dense"),
        "mlm_ln": _ln(sd, "cls.predictions.transform.LayerNorm"),
        "mlm_bias": _np(sd["cls.predictions.bias"]),
    }
    if params["token_embed"]["embedding"].shape != (
            config.vocab_size, config.hidden_size):
        raise ValueError(
            f"checkpoint embed "
            f"{params['token_embed']['embedding'].shape} != config "
            f"{(config.vocab_size, config.hidden_size)}")
    dec = sd.get("cls.predictions.decoder.weight")
    if dec is not None and not np.array_equal(
            _np(dec), params["token_embed"]["embedding"]):
        raise ValueError(
            "checkpoint's MLM decoder is not tied to the word embeddings; "
            "the native head computes logits from the tied embedding")
    for i in range(config.num_layers):
        p = f"bert.encoder.layer.{i}."
        if p + "attention.self.query.weight" not in sd:
            raise ValueError(
                f"checkpoint has {i} encoder layers, config expects "
                f"{config.num_layers}")
        params[f"layer_{i}"] = {
            "attention": {
                "query": _dense(sd, p + "attention.self.query"),
                "key": _dense(sd, p + "attention.self.key"),
                "value": _dense(sd, p + "attention.self.value"),
                "out": _dense(sd, p + "attention.output.dense"),
            },
            "attn_ln": _ln(sd, p + "attention.output.LayerNorm"),
            "mlp": {
                "wi": _dense(sd, p + "intermediate.dense"),
                "wo": _dense(sd, p + "output.dense"),
            },
            "mlp_ln": _ln(sd, p + "output.LayerNorm"),
        }
    if f"bert.encoder.layer.{config.num_layers}.attention.self.query." \
            "weight" in sd:
        n = config.num_layers
        while f"bert.encoder.layer.{n}.attention.self.query.weight" in sd:
            n += 1
        raise ValueError(
            f"checkpoint has {n} encoder layers, config expects "
            f"{config.num_layers}")
    return params


def import_bert(model_or_path, config=None, **config_overrides):
    """(native_config, params) from an HF BertForMaskedLM or local path."""
    if isinstance(model_or_path, str):
        from transformers import BertForMaskedLM

        model_or_path = BertForMaskedLM.from_pretrained(model_or_path)
    if config is None:
        config = config_from_hf_bert(model_or_path.config)
    if config_overrides:
        config = dataclasses.replace(config, **config_overrides)
    params = import_bert_state_dict(model_or_path.state_dict(), config)
    return config, params


def import_llama(model_or_path, config: Optional[LlamaConfig] = None,
                 **config_overrides):
    """(native_config, params) from an HF model instance or local path.

    ``config_overrides`` tweak the derived config (e.g. ``scan_layers=
    False``, ``seq_parallel="ring"``) — anything not changing parameter
    shapes is safe.
    """
    if isinstance(model_or_path, str):
        # Auto resolves the checkpoint's own class (Llama OR Mistral) —
        # loading a mistral checkpoint through LlamaForCausalLM would
        # keep sliding_window only by PretrainedConfig accident.
        from transformers import AutoModelForCausalLM

        model_or_path = AutoModelForCausalLM.from_pretrained(model_or_path)
    _validate_hf_llama_family(model_or_path.config)  # every path
    if config is None:
        config = config_from_hf(model_or_path.config)
    if config_overrides:
        config = dataclasses.replace(config, **config_overrides)
    # Checkpoint-vs-config guards run on the FINAL config — after
    # ``config_overrides`` — so an override can neither bypass them
    # (e.g. ``rope_scaling=None`` on a matching preset) nor trip them
    # when it brings the config INTO agreement with the checkpoint.
    # The rope-scaling rule is the CHECKPOINT's, not the preset's:
    # base Llama-3 weights under a llama31 preset (or 3.1 weights
    # under a scaling-less config — identical shapes either way)
    # would apply frequencies the weights were never trained with,
    # silently changing logits at every position.
    rs = getattr(model_or_path.config, "rope_scaling", None)
    want = ((float(rs["factor"]), float(rs["low_freq_factor"]),
             float(rs["high_freq_factor"]),
             int(rs["original_max_position_embeddings"]))
            if rs else None)
    have = getattr(config, "rope_scaling", None)
    if want != have:
        raise ValueError(
            f"config rope_scaling={have} but the checkpoint says "
            f"{want} — the checkpoint's convention wins; use a "
            "matching config/preset")
    # Same rule for the norm epsilon: shape-invisible, so a preset
    # left at the family default (1e-5 vs Qwen2.5's 1e-6) would import
    # into silently-different logits.
    hf_eps = getattr(model_or_path.config, "rms_norm_eps", None)
    if hf_eps is not None and float(hf_eps) != float(config.rms_epsilon):
        raise ValueError(
            f"config rms_epsilon={config.rms_epsilon} but the "
            f"checkpoint says rms_norm_eps={hf_eps} — the checkpoint's "
            "convention wins; use a matching config/preset")
    # And for the Gemma-convention knobs: all three are shape-invisible
    # (a sqrt(d_model) embedding multiply, the +1 zero-centered norm
    # scale, the MLP activation), so a mismatched config — a Gemma
    # checkpoint under a Llama preset or vice versa — would import
    # cleanly and silently change every forward.  The checkpoint's
    # model_type decides, exactly like the rope_scaling rule above.
    gemma = getattr(model_or_path.config, "model_type", "") == "gemma"
    want_knobs = (gemma, gemma, "gelu" if gemma else "silu")
    have_knobs = (bool(getattr(config, "embed_scale", False)),
                  bool(getattr(config, "norm_zero_centered", False)),
                  getattr(config, "mlp_activation", "silu"))
    if want_knobs != have_knobs:
        mt = getattr(model_or_path.config, "model_type", "llama")
        raise ValueError(
            f"config (embed_scale, norm_zero_centered, mlp_activation)"
            f"={have_knobs} but the checkpoint's model_type={mt!r} "
            f"requires {want_knobs} (the Gemma conventions come as a "
            "set) — the checkpoint's convention wins; use a matching "
            "config/preset")
    params = import_llama_state_dict(model_or_path.state_dict(), config)
    return config, params



def _probe_count(sd, key_fmt: str, expected: int, what: str) -> None:
    """Two-sided presence check for indexed checkpoint entries: index
    ``expected`` must be absent and ``expected - 1`` present, else count
    the real number and fail at the boundary (not with a KeyError
    mid-mapping / silent truncation).  Shared by every family importer."""
    def _has(i):
        return key_fmt.format(i) in sd

    if _has(expected) or not _has(expected - 1):
        n = 0
        while _has(n):
            n += 1
        raise ValueError(
            f"checkpoint has {n} {what}, config expects {expected}")


def _lm_head_or_tied(sd, embed: np.ndarray) -> np.ndarray:
    """``lm_head.weight`` transposed, or the tied-embedding fallback."""
    if "lm_head.weight" in sd:
        return _np(sd["lm_head.weight"]).T
    return embed.T.copy()


def _validate_hf_mixtral(hf_config) -> None:
    """Exact-or-rejected guards — run on EVERY import path, including
    the CLI's config=task_cfg route (which skips config derivation)."""
    if getattr(hf_config, "model_type", "") != "mixtral":
        raise ValueError(
            f"expected model_type='mixtral', got "
            f"{getattr(hf_config, 'model_type', None)!r}")
    if getattr(hf_config, "sliding_window", None):
        raise ValueError(
            "checkpoint sets sliding_window; the native MoE attention is "
            "full-causal — importing would silently change logits "
            "(Mixtral-8x7B weights are trained/served full-attention; "
            "re-export the checkpoint with sliding_window=null)")
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError("rope_scaling is not implemented natively")


def config_from_hf_mixtral(hf_config) -> "MoeConfig":
    """Derive a native ``MoeConfig`` from a HF ``MixtralConfig``.

    ``capacity_factor`` defaults to ``num_experts / top_k``: with that
    capacity no token can ever be dropped (each token lands on at most
    one slot per expert), so the GShard capacity dispatch computes
    EXACTLY HF's dense top-k renormalized mixture — the forward-parity
    contract.  Production fine-tunes may lower it afterwards.
    """
    from tensorflow_train_distributed_tpu.models.moe import MoeConfig

    _validate_hf_mixtral(hf_config)
    e = hf_config.num_local_experts
    k = hf_config.num_experts_per_tok
    return MoeConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=hf_config.num_key_value_heads,
        ffn_size=hf_config.intermediate_size,
        num_experts=e,
        top_k=k,
        capacity_factor=float(e) / float(k),
        moe_every=1,
        max_positions=hf_config.max_position_embeddings,
        rope_base=hf_config.rope_theta,
        rms_epsilon=hf_config.rms_norm_eps,
    )


def _mixtral_layer_tree(sd, i: int, num_experts: int) -> dict:
    """One Mixtral decoder layer → native MoeDecoderBlock param tree.

    HF expert weights: ``w1`` = gate, ``w3`` = up, ``w2`` = down (torch
    [out, in] → transpose), stacked over the expert axis exactly like
    the native ``nn.vmap`` layout.  Router ``gate.weight`` [E, d] → the
    f32 router kernel [d, E].
    """
    p = f"model.layers.{i}."
    moe = p + "block_sparse_moe."
    def expert(e, w):
        return _np(sd[moe + f"experts.{e}.{w}.weight"]).T

    return {
        "attn_norm": {"scale": _np(sd[p + "input_layernorm.weight"])},
        "attention": {
            "query": {"kernel": _np(sd[p + "self_attn.q_proj.weight"]).T},
            "key": {"kernel": _np(sd[p + "self_attn.k_proj.weight"]).T},
            "value": {"kernel": _np(sd[p + "self_attn.v_proj.weight"]).T},
            "out": {"kernel": _np(sd[p + "self_attn.o_proj.weight"]).T},
        },
        "mlp_norm": {"scale": _np(sd[p + "post_attention_layernorm.weight"])},
        "moe": {
            "router": {"kernel": _np(sd[moe + "gate.weight"]).T},
            "experts": {
                "wi_gate": {"kernel": np.stack(
                    [expert(e, "w1") for e in range(num_experts)])},
                "wi_up": {"kernel": np.stack(
                    [expert(e, "w3") for e in range(num_experts)])},
                "wo": {"kernel": np.stack(
                    [expert(e, "w2") for e in range(num_experts)])},
            },
        },
    }


def import_mixtral_state_dict(state_dict, config) -> dict:
    """HF ``MixtralForCausalLM`` state dict → native ``MoeLmModel``
    params (per-layer ``layer_{i}`` modules — the MoE stack is a Python
    loop, not a depth scan)."""
    if getattr(config, "shared_expert_size", None):
        # Symmetric with export_hf's guard: Mixtral checkpoints carry no
        # shared expert, so the mapped tree would be missing shared_mlp
        # and the first apply() would die with an opaque flax scope
        # error instead of this boundary message.
        raise ValueError(
            "HF Mixtral has no shared expert; import with "
            "shared_expert_size=None (the checkpoint cannot populate "
            f"shared_mlp, config asks for {config.shared_expert_size})")
    sd = state_dict
    embed = _np(sd["model.embed_tokens.weight"])
    if embed.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"checkpoint embed is {embed.shape}, config expects "
            f"{(config.vocab_size, config.d_model)}")
    _probe_count(sd, "model.layers.{}.input_layernorm.weight",
                 config.num_layers, "decoder layers")
    _probe_count(sd, "model.layers.0.block_sparse_moe.experts.{}.w1.weight",
                 config.num_experts, "experts per layer")
    lm_head = _lm_head_or_tied(sd, embed)
    params = {
        "token_embed": {"embedding": embed},
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
        "lm_head": {"kernel": lm_head},
    }
    for i in range(config.num_layers):
        params[f"layer_{i}"] = _mixtral_layer_tree(sd, i,
                                                   config.num_experts)
    return params


def import_mixtral(model_or_path, config=None, **config_overrides):
    """(native MoeConfig, params) from an HF Mixtral model or local path."""
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        model_or_path = AutoModelForCausalLM.from_pretrained(model_or_path)
    _validate_hf_mixtral(model_or_path.config)  # every path, config= too
    if config is None:
        config = config_from_hf_mixtral(model_or_path.config)
    elif "capacity_factor" not in config_overrides:
        # The parity contract holds only at capacity E/k (no drops) —
        # a preset's production capacity_factor (e.g. 1.25) would drop
        # tokens from step 0 and silently diverge from the HF forward.
        # Callers who explicitly want a tighter capacity pass it as an
        # override.
        hf = model_or_path.config
        config = dataclasses.replace(
            config, capacity_factor=(
                float(hf.num_local_experts) / hf.num_experts_per_tok))
    if config_overrides:
        config = dataclasses.replace(config, **config_overrides)
    params = import_mixtral_state_dict(model_or_path.state_dict(), config)
    return config, params


# ── Qwen2-MoE (shared expert + gate, qkv biases, raw top-k gates) ──────


def _validate_hf_qwen2_moe(hf_config) -> None:
    """Exact-or-rejected guards for ``Qwen2MoeForCausalLM`` imports."""
    if getattr(hf_config, "model_type", "") != "qwen2_moe":
        raise ValueError(
            f"expected model_type='qwen2_moe', got "
            f"{getattr(hf_config, 'model_type', None)!r}")
    if getattr(hf_config, "decoder_sparse_step", 1) != 1:
        raise ValueError(
            "decoder_sparse_step != 1 (MoE on every layer) is not "
            "representable (native moe_every covers alternation, but "
            "Qwen's dense layers use intermediate_size, a THIRD ffn "
            "width the native config does not carry)")
    if getattr(hf_config, "mlp_only_layers", None):
        raise ValueError("mlp_only_layers is not representable natively")
    if (getattr(hf_config, "use_sliding_window", False)
            and getattr(hf_config, "sliding_window", None)):
        raise ValueError(
            "checkpoint enables sliding_window; the native MoE "
            "attention is full-causal — importing would silently "
            "change logits")
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError("rope_scaling is not implemented natively")
    if getattr(hf_config, "hidden_act", "silu") != "silu":
        raise ValueError(
            f"hidden_act={hf_config.hidden_act!r}; the native experts "
            "are SwiGLU (silu) only")


def config_from_hf_qwen2_moe(hf_config) -> "MoeConfig":
    """Native ``MoeConfig`` from a HF ``Qwen2MoeConfig``.

    Architectural deltas vs Mixtral, all carried by config knobs:
    shared expert (+ sigmoid scalar gate), q/k/v biases, and
    ``norm_topk_prob`` (Qwen defaults to RAW softmax gates).
    ``capacity_factor`` = E/k — the no-drop parity setting, as for
    Mixtral.
    """
    from tensorflow_train_distributed_tpu.models.moe import MoeConfig

    _validate_hf_qwen2_moe(hf_config)
    e = hf_config.num_experts
    k = hf_config.num_experts_per_tok
    return MoeConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=hf_config.num_key_value_heads,
        ffn_size=hf_config.moe_intermediate_size,
        num_experts=e,
        top_k=k,
        capacity_factor=float(e) / float(k),
        moe_every=1,
        max_positions=hf_config.max_position_embeddings,
        rope_base=hf_config.rope_theta,
        rms_epsilon=hf_config.rms_norm_eps,
        shared_expert_size=hf_config.shared_expert_intermediate_size,
        shared_expert_gate=True,
        norm_topk_prob=bool(getattr(hf_config, "norm_topk_prob", False)),
        qkv_bias=True,
    )


def _qwen2_moe_layer_tree(sd, i: int, num_experts: int) -> dict:
    """One Qwen2-MoE decoder layer → native MoeDecoderBlock tree."""
    p = f"model.layers.{i}."
    mlp = p + "mlp."

    def expert(e, w):
        return _np(sd[mlp + f"experts.{e}.{w}.weight"]).T

    def biased(name):
        return {"kernel": _np(sd[p + f"self_attn.{name}.weight"]).T,
                "bias": _np(sd[p + f"self_attn.{name}.bias"])}

    return {
        "attn_norm": {"scale": _np(sd[p + "input_layernorm.weight"])},
        "attention": {
            "query": biased("q_proj"),
            "key": biased("k_proj"),
            "value": biased("v_proj"),
            "out": {"kernel": _np(sd[p + "self_attn.o_proj.weight"]).T},
        },
        "mlp_norm": {"scale": _np(sd[p + "post_attention_layernorm.weight"])},
        "moe": {
            "router": {"kernel": _np(sd[mlp + "gate.weight"]).T},
            "experts": {
                "wi_gate": {"kernel": np.stack(
                    [expert(e, "gate_proj") for e in range(num_experts)])},
                "wi_up": {"kernel": np.stack(
                    [expert(e, "up_proj") for e in range(num_experts)])},
                "wo": {"kernel": np.stack(
                    [expert(e, "down_proj") for e in range(num_experts)])},
            },
            "shared_mlp": {
                "wi_gate": {"kernel": _np(
                    sd[mlp + "shared_expert.gate_proj.weight"]).T},
                "wi_up": {"kernel": _np(
                    sd[mlp + "shared_expert.up_proj.weight"]).T},
                "wo": {"kernel": _np(
                    sd[mlp + "shared_expert.down_proj.weight"]).T},
            },
            "shared_gate": {"kernel": _np(
                sd[mlp + "shared_expert_gate.weight"]).T},
        },
    }


def import_qwen2_moe_state_dict(state_dict, config) -> dict:
    """HF ``Qwen2MoeForCausalLM`` state dict → native ``MoeLmModel``
    params."""
    if not getattr(config, "shared_expert_size", None) or \
            not getattr(config, "shared_expert_gate", False):
        raise ValueError(
            "Qwen2-MoE checkpoints carry a gated shared expert; import "
            "with shared_expert_size set and shared_expert_gate=True "
            "(config_from_hf_qwen2_moe derives both)")
    if not getattr(config, "qkv_bias", False):
        raise ValueError(
            "Qwen2-MoE checkpoints carry q/k/v projection biases; "
            "import with qkv_bias=True (the mapped tree would carry "
            "bias entries a bias-free attention never creates)")
    sd = state_dict
    embed = _np(sd["model.embed_tokens.weight"])
    if embed.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"checkpoint embed is {embed.shape}, config expects "
            f"{(config.vocab_size, config.d_model)}")

    _probe_count(sd, "model.layers.{}.input_layernorm.weight",
                 config.num_layers, "decoder layers")
    _probe_count(sd, "model.layers.0.mlp.experts.{}.gate_proj.weight",
                 config.num_experts, "experts per layer")
    params = {
        "token_embed": {"embedding": embed},
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
        "lm_head": {"kernel": _lm_head_or_tied(sd, embed)},
    }
    for i in range(config.num_layers):
        params[f"layer_{i}"] = _qwen2_moe_layer_tree(
            sd, i, config.num_experts)
    return params


def import_qwen2_moe(model_or_path, config=None, **config_overrides):
    """(native MoeConfig, params) from an HF Qwen2-MoE model or path."""
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        model_or_path = AutoModelForCausalLM.from_pretrained(model_or_path)
    _validate_hf_qwen2_moe(model_or_path.config)
    if config is None:
        config = config_from_hf_qwen2_moe(model_or_path.config)
    else:
        hf = model_or_path.config
        if "capacity_factor" not in config_overrides:
            # Parity holds only at the no-drop capacity E/k (the
            # Mixtral importer's rule).
            config = dataclasses.replace(
                config, capacity_factor=(
                    float(hf.num_experts) / hf.num_experts_per_tok))
        if "norm_topk_prob" not in config_overrides:
            # The gate convention is the CHECKPOINT's, not the
            # preset's: a mismatch silently changes every forward
            # (raw vs renormalized top-k gates).
            config = dataclasses.replace(
                config, norm_topk_prob=bool(
                    getattr(hf, "norm_topk_prob", False)))
        if ("rms_epsilon" not in config_overrides
                and getattr(hf, "rms_norm_eps", None) is not None):
            # The norm epsilon is the checkpoint's too — shape-
            # invisible, so a preset left at the family default (1e-5
            # vs Qwen's 1e-6) would silently change every forward.
            config = dataclasses.replace(
                config, rms_epsilon=float(hf.rms_norm_eps))
    if config_overrides:
        config = dataclasses.replace(config, **config_overrides)
    params = import_qwen2_moe_state_dict(model_or_path.state_dict(),
                                         config)
    return config, params


def import_moe(model_or_path, config=None, **config_overrides):
    """Sparse-MoE import dispatch on the checkpoint's ``model_type``
    (Mixtral vs Qwen2-MoE) — local dir or hub id alike, resolved via
    ``AutoConfig`` so no weights download before the decision.  The
    single entry point launch.py / sample.py / serve.py share."""
    if isinstance(model_or_path, str):
        from transformers import AutoConfig

        mt = getattr(AutoConfig.from_pretrained(model_or_path),
                     "model_type", "")
    else:
        mt = getattr(model_or_path.config, "model_type", "")
    if mt == "qwen2_moe":
        return import_qwen2_moe(model_or_path, config,
                                **config_overrides)
    if mt != "mixtral":
        # Fail fast while only the CONFIG is in hand — falling through
        # to import_mixtral would download the full checkpoint before
        # its validator rejects the model_type.
        raise ValueError(
            f"sparse-MoE import supports mixtral and qwen2_moe, got "
            f"model_type={mt!r}")
    return import_mixtral(model_or_path, config, **config_overrides)
