"""Post-training int8 weight-only quantization for serving.

The reference is a training harness; its SFT output (SURVEY.md §2.1
config[4]) gets served elsewhere.  Our framework closes that loop
natively (``models.generate``), and this module adds the standard
serving lever on top: weight-only int8.  Decode is weight-HBM-bound —
every step reads every kernel from HBM — so storing kernels as int8
with per-output-channel scales halves the dominant traffic vs bf16
(and quarters it vs f32 masters), which is near-linear decode speedup
at small batch on TPU.

Design (TPU-first, zero model changes):

- ``quantize_params(params)`` walks a trained (unboxed) param tree and
  replaces every 2-D matmul kernel — and 3-D depth-stacked kernels from
  ``nn.scan`` models — with a symmetric int8 kernel, emitting a parallel
  ``quant`` collection holding one f32 scale per output channel.
- At apply time a flax *method interceptor* (``quantized_dense``)
  recognises any ``nn.Dense``/``nn.DenseGeneral`` whose path carries a
  scale and computes ``(x @ w_int8.astype(dtype)) * scale + bias``.
  XLA fuses the int8→bf16 convert into the matmul's weight read, so the
  kernel streams from HBM at 1 byte/param.  The bias (BERT-family
  encoders) is added after the scale, so it stays exact.
- ``models.generate`` accepts the scale tree via ``quant_scales=`` and
  runs under the interceptor; the depth scan carries the ``quant``
  collection with the same stacked layout as params.

Error bound: symmetric per-channel round-to-nearest gives
``|w - q*s| <= s/2`` with ``s = max|w_col| / 127`` — the standard
weight-only recipe (GPTQ-less), which is accuracy-neutral for decoder
LMs at 8 bits.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.traverse_util import flatten_dict, unflatten_dict

# Kernel ranks eligible for quantization: plain Dense/DenseGeneral
# kernels are [in, out]; nn.scan-stacked decoder kernels are
# [layers, in, out].  Conv kernels ([H, W, in, out], 4-D) and anything
# exotic are left untouched.
_QUANT_NDIMS = (2, 3)


def quantize_params(params, *, bits: int = 8):
    """Quantize matmul kernels of a trained param tree to int8.

    Returns ``(qparams, scales)``:

    - ``qparams``: same tree structure; every eligible ``kernel`` leaf
      replaced by a same-shape int8 array, all other leaves unchanged.
    - ``scales``: a sparse mirror tree holding ``scale`` leaves (f32,
      one per output channel; stacked kernels get ``[layers, out]``)
      at each quantized kernel's path — the ``quant`` collection that
      ``models.generate(..., quant_scales=scales)`` consumes.

    Eligible: leaves named ``kernel`` with ndim 2 or 3 and a floating
    dtype — plain Dense kernels, ``nn.scan`` depth-stacked decoder
    kernels, and ``nn.vmap`` expert-stacked MoE FFN kernels (both stack
    forms carry ``quant`` in their variable_axes, so scales slice
    alongside the kernels).  Embeddings, norms, biases and conv filters
    stay in their original dtype (the interceptor only rewrites
    ``nn.Dense``/``nn.DenseGeneral`` call sites).
    """
    if bits != 8:
        raise ValueError(f"only int8 supported, got bits={bits}")
    # Accept boxed trees (raw model.init output): strip metadata boxes
    # by VALUE (not nn.unbox, which applies sharding constraints —
    # trainer.py uses the same pattern). Trained Trainer states arrive
    # already unboxed.
    is_boxed = lambda x: isinstance(x, nn.meta.AxisMetadata)  # noqa: E731
    params = jax.tree.map(lambda x: x.value if is_boxed(x) else x,
                          params, is_leaf=is_boxed)
    flat = flatten_dict(params)
    qflat: dict = {}
    sflat: dict = {}
    for path, w in flat.items():
        if (path[-1] == "kernel" and hasattr(w, "ndim")
                and w.ndim in _QUANT_NDIMS
                and jnp.issubdtype(w.dtype, jnp.floating)):
            w32 = w.astype(jnp.float32)
            amax = jnp.max(jnp.abs(w32), axis=-2)          # [..., out]
            scale = jnp.where(amax > 0, amax / 127.0, 1.0)
            q = jnp.clip(jnp.round(w32 / scale[..., None, :]),
                         -127, 127).astype(jnp.int8)
            qflat[path] = q
            sflat[path[:-1] + ("scale",)] = scale
        else:
            qflat[path] = w
    if not sflat:
        raise ValueError(
            "no eligible matmul kernels found to quantize (expected "
            "'kernel' leaves of ndim 2/3; was this tree already "
            "quantized, or boxed? pass nn.unbox-ed params)")
    return unflatten_dict(qflat), unflatten_dict(sflat)


def dequantize_params(qparams, scales):
    """Inverse transform: int8 kernels back to f32 (for tests/tools)."""
    qflat = flatten_dict(qparams)
    sflat = flatten_dict(scales)
    out = {}
    for path, w in qflat.items():
        spath = path[:-1] + ("scale",)
        if path[-1] == "kernel" and spath in sflat:
            out[path] = w.astype(jnp.float32) * sflat[spath][..., None, :]
        else:
            out[path] = w
    return unflatten_dict(out)


def quantized_bytes(params) -> int:
    """Total parameter bytes (quantized trees count int8 kernels at 1B)."""
    return sum(x.dtype.itemsize * x.size
               for x in jax.tree.leaves(params) if hasattr(x, "dtype"))


def _quant_dense_interceptor(next_fn, args, kwargs, context):
    """Flax method interceptor: fused int8 matmul for quantized Dense.

    Fires only when the bound module is a Dense/DenseGeneral whose path
    holds a ``quant``-collection ``scale`` — everything else passes
    through untouched, so the interceptor is safe to keep active
    unconditionally (``generate`` does).
    """
    mdl = context.module
    if (context.method_name != "__call__"
            or not isinstance(mdl, (nn.Dense, nn.DenseGeneral))
            or not mdl.has_variable("quant", "scale")):
        return next_fn(*args, **kwargs)
    (x,) = args
    kernel = mdl.get_variable("params", "kernel")
    scale = mdl.get_variable("quant", "scale")
    if kernel.ndim != 2:
        raise ValueError(
            f"quantized {type(mdl).__name__} at {'/'.join(mdl.path)} has "
            f"kernel ndim {kernel.ndim}; expected 2 at call time (stacked "
            "kernels must be sliced by nn.scan before the layer runs)")
    if isinstance(mdl, nn.DenseGeneral) and not (
            isinstance(mdl.features, int) and mdl.axis == -1):
        raise ValueError(
            "quantized DenseGeneral supports the Dense-shaped case "
            f"(int features, axis=-1); got features={mdl.features!r} "
            f"axis={mdl.axis!r}")
    dtype = mdl.dtype or x.dtype
    # (x @ q) * scale: the per-OUTPUT-channel scale commutes with the
    # contraction, so the int8 kernel feeds the MXU directly and the
    # convert fuses into its HBM read.
    y = jax.lax.dot_general(
        x.astype(dtype), kernel.astype(dtype),
        (((x.ndim - 1,), (0,)), ((), ())))
    y = y * scale.astype(dtype)
    if mdl.use_bias:
        y = y + mdl.get_variable("params", "bias").astype(dtype)
    return y


def quantized_inference():
    """Context manager activating the int8 Dense path for any
    ``model.apply`` whose variables include a ``quant`` collection."""
    return nn.intercept_methods(_quant_dense_interceptor)


def check_quant_pairing(params, quant_scales: Optional[Any]) -> None:
    """int8 kernels and their scale tree must travel together.

    Either pairing mistake yields plausibly-shaped garbage tokens
    (unscaled int8 matmuls, or scales applied to full-precision
    kernels) — fail loudly instead.  Shared by ``models.generate`` and
    ``serving.ServingEngine`` so the contract cannot drift.
    """
    import jax
    import jax.numpy as jnp

    has_int8 = any(
        getattr(x, "dtype", None) == jnp.int8
        for x in jax.tree.leaves(params))
    if has_int8 != (quant_scales is not None):
        raise ValueError(
            "int8 params and quant_scales must be passed together: got "
            f"int8 kernels={has_int8}, quant_scales="
            f"{'set' if quant_scales is not None else 'None'} "
            "(both come from models.quant.quantize_params)")


def maybe_quant_variables(params, quant_scales: Optional[Any]) -> dict:
    """Assemble the apply-variables dict, attaching ``quant`` if given."""
    variables = {"params": params}
    if quant_scales is not None:
        variables["quant"] = quant_scales
    return variables
