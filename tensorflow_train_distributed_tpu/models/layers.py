"""Shared transformer building blocks with logical-axis shardings.

One set of layers serves BERT (config[2]), Transformer-big (config[3]) and
Llama (config[4]).  Every weight and activation carries logical axis names
(``parallel.sharding`` vocabulary), so the same module tensor-parallelizes
under dp×tp, sequence-parallelizes under dp×sp, and fsdp-shards under fsdp —
the DTensor-Layout role from the reference's stretch config, without
per-strategy model code.

Megatron-style TP falls out of the annotations: qkv/mlp-in kernels shard
their *output* dim on ``tensor`` (("embed","heads"), ("embed","mlp")),
out-proj/mlp-out shard their *input* dim (("heads","embed") is not used —
("mlp","embed") etc.), so GSPMD inserts exactly the two allreduces per block
Megatron prescribes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_train_distributed_tpu.runtime import compat
from tensorflow_train_distributed_tpu.ops.attention import (
    multihead_attention_kernel,
)

Dtype = Any


def _active_mesh(axis: str):
    """The ambient (abstract) mesh if it shards ``axis``, else None."""
    mesh = compat.get_abstract_mesh()
    if mesh is None or mesh.empty or mesh.shape.get(axis, 1) <= 1:
        return None
    return mesh


def _seq_parallel_mesh(seq_parallel: Optional[str]):
    """The ambient (abstract) mesh when SP is requested and usable."""
    if seq_parallel is None:
        return None
    return _active_mesh("seq")


def dense(features, logical_axes, *, use_bias=True, dtype=jnp.float32,
          name=None, kernel_init=None):
    return nn.DenseGeneral(
        features, use_bias=use_bias, dtype=dtype, name=name,
        kernel_init=nn.with_logical_partitioning(
            kernel_init or nn.initializers.lecun_normal(), logical_axes),
    )


class Embed(nn.Module):
    """Token embedding, vocab-sharded, with optional logit tying."""

    vocab_size: int
    features: int
    dtype: Dtype = jnp.float32

    def setup(self):
        self.embedding = self.param(
            "embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=1.0), ("vocab", "embed")),
            (self.vocab_size, self.features),
        )

    def __call__(self, ids):
        emb = self.embedding.astype(self.dtype)
        if _active_mesh("fsdp") is not None:
            # ZeRO-3 semantics: gather the table's embed shards at the
            # use site so the output is born batch-sharded.  Without
            # this the output inherits the table's embed→fsdp sharding
            # and SPMD can only transition an activation from embed- to
            # batch-sharding by involuntary full rematerialization
            # (replicate-then-partition, warned by spmd_partitioner) —
            # wasted HBM + ICI every step on real multi-chip hardware.
            # "vocab" stays as annotated (tensor-sharded): only the
            # embed/fsdp dim needed gathering, and a (None, None)
            # constraint would all-gather the table over tensor too
            # (~260 MB/chip extra at llama2_7b scale).
            emb = nn.with_logical_constraint(emb, ("vocab", None))
        x = jnp.take(emb, ids, axis=0)
        return nn.with_logical_constraint(x, ("batch", "length", "embed"))

    def attend(self, x):
        """Tied output logits: x @ E^T (used by Llama/BERT heads)."""
        return jnp.einsum("ble,ve->blv", x, self.embedding.astype(x.dtype))


def sinusoidal_positions(seq_len: int, features: int) -> np.ndarray:
    """Fixed sin/cos table (Transformer-big / reference Keras convention)."""
    pos = np.arange(seq_len)[:, None]
    div = np.exp(np.arange(0, features, 2) / features * -np.log(10000.0))
    table = np.zeros((seq_len, features), np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div)
    return table


def llama3_scaled_freqs(freqs, scaling):
    """Llama-3.x frequency-dependent RoPE scaling (HF
    ``_compute_llama3_parameters``): long wavelengths divide by
    ``factor``, short ones stay, the middle band interpolates smoothly.
    ``scaling`` = (factor, low_freq_factor, high_freq_factor,
    original_max_positions)."""
    factor, low, high, old_len = scaling
    wavelen = 2.0 * np.pi / freqs
    low_wl = old_len / low
    high_wl = old_len / high
    scaled = jnp.where(wavelen > low_wl, freqs / factor, freqs)
    smooth = (old_len / wavelen - low) / (high - low)
    smoothed = (1.0 - smooth) / factor * freqs + smooth * freqs
    medium = (wavelen >= high_wl) & (wavelen <= low_wl)
    return jnp.where(medium, smoothed, scaled)


def apply_rope(x, positions, *, base: float = 10000.0, scaling=None):
    """RoPE applied to [B, S, H, D] at integer ``positions`` [B, S].

    Applied separately to q and k so each uses its own positions (KV-cache
    decode and cross-length attention need different q/k position vectors).
    ``scaling``: optional llama3 rope-scaling tuple (see
    ``llama3_scaled_freqs``).
    """
    head_dim = x.shape[-1]
    freqs = 1.0 / base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim)
    if scaling is not None:
        freqs = llama3_scaled_freqs(freqs, scaling)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    cos = jnp.cos(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def _quantize_kv_rows(t):
    """Symmetric int8 quantization of KV rows, one f32 scale per
    (..., row, kv_head) amax'd over head_dim — THE one KV quantization
    recipe.  The linear cache, the per-slot serving cache, and the
    paged block pool all store exactly these values, which is what
    makes the cross-layout int8 parity bitwise (pinned in
    tests/test_serving_paged.py)."""
    amax = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    qt = jnp.clip(jnp.round(t.astype(jnp.float32) / scale[..., None]),
                  -127, 127).astype(jnp.int8)
    return qt, scale


class RMSNorm(nn.Module):
    """Llama-family norm; scale is replicated ("norm" logical axis).

    ``zero_centered`` (the Gemma convention): output = x̂ · (1 + scale)
    with zeros-init — the parameter stores the DEVIATION from identity,
    so weight decay pulls toward identity and HF Gemma checkpoints map
    verbatim."""

    epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    zero_centered: bool = False

    @nn.compact
    def __call__(self, x):
        from tensorflow_train_distributed_tpu.ops.pallas_kernels import (
            rms_norm,
        )

        init = (nn.initializers.zeros if self.zero_centered
                else nn.initializers.ones)
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(init, ("norm",)),
            (x.shape[-1],),
        )
        if self.zero_centered:
            scale = scale + 1.0
        # Fused pallas kernel on TPU (one VMEM pass, custom VJP); the
        # reference jnp path elsewhere — identical numerics (f32 accum).
        return rms_norm(x, scale, epsilon=self.epsilon).astype(self.dtype)


class MultiHeadAttention(nn.Module):
    """MHA/GQA over the shared attention kernel.

    Weights: q/k/v ("embed", "heads", "kv"); out ("heads", "kv", "embed").
    Activations constrained to ("batch", "length", "heads", "kv") so a seq
    axis shards length and a tensor axis shards heads.
    """

    num_heads: int
    head_dim: int
    num_kv_heads: Optional[int] = None  # GQA; None → MHA
    dtype: Dtype = jnp.float32
    causal: bool = False
    use_rope: bool = False
    rope_base: float = 10000.0
    # Llama-3.x rope scaling tuple (factor, low_freq_factor,
    # high_freq_factor, original_max_positions); None = plain RoPE.
    rope_scaling: Optional[tuple] = None
    dropout_rate: float = 0.0
    # Sequence/context parallelism: "ring" | "ulysses" | None.  Takes
    # effect when the ambient mesh (jax.set_mesh, as the Trainer binds)
    # has a seq axis > 1; self-attention only.
    seq_parallel: Optional[str] = None
    # Sliding-window causal attention (Mistral convention): each query
    # sees the last ``window`` keys including itself.  Long training
    # sequences take the O(S·window) chunked path; decode keeps a
    # rolling window-sized KV cache.  Composes with ring/Ulysses SP.
    window: Optional[int] = None
    # StreamingLLM attention sinks (needs ``window``): the first
    # ``sinks`` positions stay attendable past the window — keeps
    # unbounded streaming decode stable.  Decode stores them in a small
    # separate buffer beside the rolling ring; both SP methods compose
    # (ring broadcasts shard 0's sink block with one tiny psum).
    sinks: int = 0
    # Autoregressive decode: keep a KV cache of ``cache_len`` positions in
    # the mutable "cache" collection; each call appends this call's k/v at
    # the running index and attends over the filled prefix.  Works for
    # prefill (q_len = prompt length) and stepping (q_len = 1) alike.
    decode: bool = False
    cache_len: int = 0
    # int8 KV cache (decode only): rows quantize per (position,
    # kv_head) with an f32 scale — halves cache HBM vs bf16 (cache
    # reads dominate large-batch/long-context decode) and the dequant
    # fuses into the attention read.  Composes with the shared-index
    # linear cache, the per-slot serving cache, AND the paged block
    # pool (scales ride in a parallel pool var).  Unsupported with the
    # rolling window cache (roll/concat would need scale plumbing; the
    # window already bounds cache memory).
    kv_cache_int8: bool = False
    # Per-slot decode (continuous-batching serving, serving.ServingEngine): the
    # cache index is a VECTOR [B] — each batch row ("slot") sits at its
    # own position, so requests of different lengths decode together and
    # a finished slot can be refilled mid-flight.  Writes become
    # per-row scatters and the causal mask goes per-slot; RoPE reads
    # each slot's own position.  Linear cache, full-precision or
    # kv_cache_int8 (window/sinks keep the shared-index fast path).
    slot_decode: bool = False
    # Paged KV cache (serving.ServingEngine paged mode; needs
    # slot_decode): instead of one contiguous [B, cache_len] strip per
    # lane, KV rows live in a FIXED pool of ``paged_kv_blocks`` physical
    # blocks of ``kv_block_size`` rows, and each lane maps its logical
    # positions through a per-lane block table (a [B, ceil(cache_len /
    # kv_block_size)] cache variable the engine rewrites host-side at
    # insert/retire).  Shapes stay static — the pool never grows — so
    # jit/sharding see the same program session-long; only table
    # CONTENTS change, which is what lets requests share prompt-prefix
    # blocks copy-on-write (serving_kv.RadixPrefixIndex).  Block 0 is
    # the engine's scratch block: idle/retired lanes' garbage writes
    # land there (their table rows are zeroed), the paged analog of the
    # linear cache's stale-row rule.
    paged_kv_blocks: int = 0
    kv_block_size: int = 0
    # Projection biases (BERT-style encoders; Llama-family stays False).
    use_bias: bool = False
    # q/k/v biases ONLY, out-proj unbiased (the Qwen-family convention;
    # ``use_bias`` keeps the all-projection BERT meaning).
    qkv_bias: bool = False
    # Fuse q/k/v into ONE gemm ("qkv" kernel, [embed, (H+2·KV)·D]).
    # MFU lever for small decoders where three launch-bound projections
    # under-fill the MXU; self-attention only, and the param tree
    # differs from the split layout (checkpoints are not interchangeable
    # — pick per config, before training).  Under a tensor mesh the
    # post-gemm q/k/v slices cut across the fused dim's shards, so keep
    # it for single-chip/dp serving and training runs.
    fused_qkv: bool = False

    def _proj(self, x, heads, name):
        # Plain 2-D kernel (embed, heads*head_dim) + reshape: maps onto
        # the MXU as one big matmul, and sidesteps flax's DenseGeneral
        # boxed-kernel reshape which mis-applies logical constraints
        # under an active mesh.  "heads" on the fused dim still gives
        # Megatron TP (heads*head_dim stays divisible by the tensor
        # axis whenever heads is).  Shared by the training and decode
        # paths — the submodule name/init/partitioning contract between
        # them lives here and only here.
        y = nn.Dense(
            heads * self.head_dim,
            use_bias=self.use_bias or self.qkv_bias, dtype=self.dtype,
            name=name,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "heads")),
        )(x)
        y = y.reshape(*x.shape[:-1], heads, self.head_dim)
        return nn.with_logical_constraint(
            y, ("batch", "length", self._head_ax(heads), "kv"))

    def _qkv(self, x):
        """Self-attention q/k/v: three gemms, or one fused gemm
        (``fused_qkv``) split head-wise after the reshape."""
        kv_heads = self.num_kv_heads or self.num_heads
        if not self.fused_qkv:
            return (self._proj(x, self.num_heads, "query"),
                    self._proj(x, kv_heads, "key"),
                    self._proj(x, kv_heads, "value"))
        tot = self.num_heads + 2 * kv_heads
        y = nn.Dense(
            tot * self.head_dim, use_bias=self.use_bias or self.qkv_bias,
            dtype=self.dtype, name="qkv",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "heads")),
        )(x)
        y = y.reshape(*x.shape[:-1], tot, self.head_dim)
        y = nn.with_logical_constraint(
            y, ("batch", "length", self._head_ax(tot), "kv"))
        return (y[..., :self.num_heads, :],
                y[..., self.num_heads:self.num_heads + kv_heads, :],
                y[..., self.num_heads + kv_heads:, :])

    def _head_ax(self, heads):
        """Logical axis for a ``heads``-sized activation dim.

        GQA with fewer kv heads than the tensor degree ("heads" maps to
        the tensor axis in DEFAULT_RULES): replicate the head axis
        instead of letting GSPMD pad-shard a 2-head dim over 4 ways and
        relayout it inside the decode while-loop by involuntary full
        rematerialization (caught by the driver dryrun's sharded-serving
        step, which asserts on the warning)."""
        mesh = _active_mesh("tensor")
        if mesh is not None and heads % mesh.shape["tensor"]:
            return None
        return "heads"

    def _out_proj(self, x, features):
        return nn.Dense(
            features, use_bias=self.use_bias, dtype=self.dtype, name="out",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("heads", "embed")),
        )(x)

    @nn.compact
    def __call__(self, x_q, x_kv=None, *, mask=None, positions=None,
                 segment_ids=None, deterministic: bool = True):
        if self.decode:
            if (x_kv is not None or mask is not None
                    or segment_ids is not None or positions is not None):
                raise ValueError(
                    "decode=True is causal self-attention over the KV "
                    "cache; cross-attention inputs (x_kv), dense masks, "
                    "segment ids and explicit positions are not supported "
                    "in decode mode (the cache index supplies positions)")
            return self._decode_step(x_q)
        if self.slot_decode:
            raise ValueError("slot_decode requires decode=True (it is a "
                             "KV-cache mode)")
        if self.paged_kv_blocks:
            raise ValueError("paged_kv_blocks requires decode=True + "
                             "slot_decode=True (it is a serving KV-cache "
                             "mode)")
        if segment_ids is not None and x_kv is not None:
            raise ValueError(
                "segment_ids (sequence packing) applies to self-attention "
                "only")
        x_kv = x_q if x_kv is None else x_kv
        kv_heads = self.num_kv_heads or self.num_heads

        if x_kv is x_q:
            q, k, v = self._qkv(x_q)
        else:
            if self.fused_qkv:
                raise ValueError("fused_qkv is self-attention only "
                                 "(q and kv read different inputs)")
            q = self._proj(x_q, self.num_heads, "query")
            k = self._proj(x_kv, kv_heads, "key")
            v = self._proj(x_kv, kv_heads, "value")

        if self.use_rope:
            if positions is None:
                # Default q positions follow the causal-mask alignment: for
                # causal cross-length attention q is the *suffix* of the kv
                # sequence (bottom-right alignment), so its positions start
                # at kv_len - q_len; callers with other layouts (KV cache at
                # arbitrary offsets) pass explicit ``positions``.
                offset = x_kv.shape[1] - x_q.shape[1] if self.causal else 0
                positions = jnp.broadcast_to(
                    jnp.arange(x_q.shape[1]) + offset, x_q.shape[:2])
            # Self-attention with caller positions (packed segments):
            # keys live at the SAME positions as their queries.
            kv_positions = (positions if x_kv is x_q
                            else jnp.broadcast_to(
                                jnp.arange(x_kv.shape[1]), x_kv.shape[:2]))
            q = apply_rope(q, positions, base=self.rope_base,
                           scaling=self.rope_scaling)
            k = apply_rope(k, kv_positions, base=self.rope_base,
                           scaling=self.rope_scaling)

        # [B, S, H, D] → [B, H, S, D] for the kernel.
        qh, kh, vh = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        sp_mesh = _seq_parallel_mesh(self.seq_parallel)
        if sp_mesh is None and kv_heads != self.num_heads:
            # GQA: repeat KV groups to full heads (XLA fuses the broadcast).
            # The SP path rotates/reshards the *unrepeated* KV and repeats
            # inside the shard_map body, saving ICI traffic.
            rep = self.num_heads // kv_heads
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        if sp_mesh is not None:
            if mask is not None:
                raise ValueError(
                    "seq_parallel attention supports causal/full (+ packed "
                    "segment_ids), not dense masks")
            if x_kv is not x_q:
                raise ValueError("seq_parallel supports self-attention only")
            from tensorflow_train_distributed_tpu.parallel.ring_attention \
                import shard_mapped_attention

            out = shard_mapped_attention(
                sp_mesh, qh, kh, vh, method=self.seq_parallel,
                causal=self.causal, segment_ids=segment_ids,
                window=self.window, sinks=self.sinks,
            ).transpose(0, 2, 1, 3)
        else:
            out = multihead_attention_kernel(
                qh, kh, vh, causal=self.causal, mask=mask,
                segment_ids=segment_ids, window=self.window,
                sinks=self.sinks,
            ).transpose(0, 2, 1, 3)
        out = nn.with_logical_constraint(
            out, ("batch", "length", self._head_ax(self.num_heads), "kv"))
        if self.dropout_rate > 0 and not deterministic:
            out = nn.Dropout(self.dropout_rate)(out,
                                                deterministic=deterministic)
        out = out.reshape(*out.shape[:-2],
                          self.num_heads * self.head_dim)
        y = self._out_proj(out, x_q.shape[-1])
        return nn.with_logical_constraint(y, ("batch", "length", "embed"))

    def _decode_step(self, x):
        """Append x's tokens to the KV cache, attend over the prefix.

        Submodule names match the training path exactly, so params trained
        (or imported) without decode load unchanged; only the "cache"
        collection is new.  Causal structure comes from the index mask, not
        the kernel — decode q_len is tiny, the einsum path is the right
        tool.

        With ``window`` set and ``cache_len > window``, the cache is a
        ROLLING ring buffer of ``window`` rows (slot = position %% window)
        — serving memory and per-step attention cost scale with the
        window, not the total generation length (Mistral 32k decode keeps
        a 4k cache/layer).  Multi-token calls work at any position
        (first prefill, chunked prefill, speculative blocks): the block
        attends over (unrolled ring, fresh block) with the window band,
        and the last ``window`` positions re-pack into the ring.
        """
        if self.cache_len <= 0:
            raise ValueError("decode=True needs cache_len > 0")
        if self.paged_kv_blocks and not self.slot_decode:
            raise ValueError(
                "paged_kv_blocks requires slot_decode=True (the paged "
                "pool is the serving engine's per-lane cache mode)")
        if self.slot_decode:
            if self.window is not None or self.sinks:
                raise ValueError(
                    "slot_decode (per-slot cache positions) supports the "
                    "LINEAR cache only (full-precision or kv_cache_int8) "
                    "— window/sinks keep the shared-index path")
            if self.paged_kv_blocks:
                if self.paged_kv_blocks < 2:
                    raise ValueError(
                        "paged_kv_blocks must be >= 2 (block 0 is the "
                        f"reserved scratch block), got "
                        f"{self.paged_kv_blocks}")
                if self.kv_block_size < 1:
                    raise ValueError(
                        f"kv_block_size must be >= 1, got "
                        f"{self.kv_block_size}")
                return self._paged_decode_step(x)
            return self._slot_decode_step(x)
        if self.sinks and (self.window is None
                           or self.sinks > self.window):
            raise ValueError(
                f"sinks={self.sinks} needs a sliding window >= sinks, "
                f"got window={self.window}")
        rolling = (self.window is not None
                   and self.cache_len > self.window)
        if self.kv_cache_int8 and (rolling or self.sinks):
            raise ValueError(
                "kv_cache_int8 supports the LINEAR cache only (the "
                "rolling window ring / sink buffers would need scale "
                "plumbing through roll/concat, and the window already "
                "bounds cache memory)")
        cache_rows = self.window if rolling else self.cache_len
        kv_heads = self.num_kv_heads or self.num_heads
        b, q_len, _ = x.shape
        # STATIC first-call signal: the cache collection does not exist
        # yet on the very first apply (generate's prefill) — a Python
        # bool, trustworthy under jit, unlike sniffing whether `cur` is
        # a tracer (inside jit even the fresh-init zero is one).
        fresh_cache = not self.has_variable("cache", "index")

        q, k, v = self._qkv(x)

        cache_dtype = jnp.int8 if self.kv_cache_int8 else self.dtype
        cache_k = self.variable(
            "cache", "key_cache", jnp.zeros,
            (b, cache_rows, kv_heads, self.head_dim), cache_dtype)
        cache_v = self.variable(
            "cache", "value_cache", jnp.zeros,
            (b, cache_rows, kv_heads, self.head_dim), cache_dtype)
        if self.kv_cache_int8:
            # One f32 scale per (batch, row, kv_head): symmetric over the
            # head_dim — the standard per-token KV quantization grain.
            kv_scales = self.variable(
                "cache", "kv_scales", jnp.zeros,
                (2, b, cache_rows, kv_heads), jnp.float32)
        index = self.variable(
            "cache", "index", lambda: jnp.zeros((), jnp.int32))
        cur = index.value

        positions = cur + jnp.arange(q_len)
        if self.use_rope:
            pos_b = jnp.broadcast_to(positions, (b, q_len))
            q = apply_rope(q, pos_b, base=self.rope_base,
                           scaling=self.rope_scaling)
            k = apply_rope(k, pos_b, base=self.rope_base,
                           scaling=self.rope_scaling)
        index.value = cur + q_len

        if rolling and q_len > 1:
            return self._rolling_block(x, q, k, v, cache_k, cache_v,
                                       cur, kv_heads, b, q_len,
                                       fresh_cache)

        kdt = cache_k.value.dtype
        if rolling:
            # Single-token step: own slot = cur % window; slot j then
            # holds absolute position cur - ((cur - j) % window), which
            # is automatically within the window — unfilled slots
            # (negative position) and slots the SINK buffer serves
            # (position < sinks) are masked out.
            w = self.window
            slot = jnp.mod(cur, w)
            cache_k.value = jax.lax.dynamic_update_slice(
                cache_k.value, k.astype(kdt), (0, slot, 0, 0))
            cache_v.value = jax.lax.dynamic_update_slice(
                cache_v.value, v.astype(kdt), (0, slot, 0, 0))
            j = jnp.arange(w)
            slot_pos = cur - jnp.mod(cur - j, w)  # mod ≥ 0 (Python sem.)
            # Exclusivity: the sink buffer serves positions < sinks, the
            # ring serves >= sinks — uniform at every cur, no double
            # counting even while the sink range itself is decoding.
            mask = (slot_pos >= max(self.sinks, 0))[None, :]  # [1, cache]
            kc, vc = cache_k.value, cache_v.value
            if self.sinks:
                sink_k, sink_v = self._sink_buffers(b, kv_heads)
                self._write_sinks(sink_k, sink_v, k, v, cur, q_len, kdt)
                kc = jnp.concatenate([sink_k.value, kc], axis=1)
                vc = jnp.concatenate([sink_v.value, vc], axis=1)
                # Causal: sink position si visible once decoded (si <=
                # cur); unwritten rows are > cur and excluded with it.
                mask = jnp.concatenate(
                    [(jnp.arange(self.sinks) <= cur)[None, :], mask],
                    axis=1)
            return self._cache_attend(q, kc, vc, mask[None, None],
                                      kv_heads, b, q_len, x.shape[-1])
        if self.kv_cache_int8:
            # Quantize this call's rows: amax over head_dim per
            # (batch, position, kv_head) — the shared recipe.
            qk, sk = _quantize_kv_rows(k)
            qv, sv = _quantize_kv_rows(v)
            cache_k.value = jax.lax.dynamic_update_slice(
                cache_k.value, qk, (0, cur, 0, 0))
            cache_v.value = jax.lax.dynamic_update_slice(
                cache_v.value, qv, (0, cur, 0, 0))
            kv_scales.value = jax.lax.dynamic_update_slice(
                kv_scales.value, jnp.stack([sk, sv]), (0, 0, cur, 0))
            # Dequant at read: XLA fuses the convert+multiply into the
            # attention einsum's cache read (int8 bytes off HBM).
            kc = (cache_k.value.astype(self.dtype)
                  * kv_scales.value[0][..., None].astype(self.dtype))
            vc = (cache_v.value.astype(self.dtype)
                  * kv_scales.value[1][..., None].astype(self.dtype))
        else:
            cache_k.value = jax.lax.dynamic_update_slice(
                cache_k.value, k.astype(kdt), (0, cur, 0, 0))
            cache_v.value = jax.lax.dynamic_update_slice(
                cache_v.value, v.astype(kdt), (0, cur, 0, 0))
            kc, vc = cache_k.value, cache_v.value
        kv_pos = jnp.arange(cache_rows)
        mask = kv_pos[None, :] <= positions[:, None]   # [q, cache]
        if self.window is not None:
            # Linear cache + window: the last `window` positions
            # (including self) and the sink prefix stay visible.
            band = kv_pos[None, :] > positions[:, None] - self.window
            if self.sinks:
                band = jnp.logical_or(band, (kv_pos < self.sinks)[None, :])
            mask = jnp.logical_and(mask, band)
        return self._cache_attend(q, kc, vc,
                                  mask[None, None], kv_heads, b, q_len,
                                  x.shape[-1])

    def _sink_buffers(self, b, kv_heads):
        """The StreamingLLM sink KV buffer pair ([B, sinks, Hkv, D])."""
        sink_k = self.variable(
            "cache", "sink_key", jnp.zeros,
            (b, self.sinks, kv_heads, self.head_dim), self.dtype)
        sink_v = self.variable(
            "cache", "sink_value", jnp.zeros,
            (b, self.sinks, kv_heads, self.head_dim), self.dtype)
        return sink_k, sink_v

    def _write_sinks(self, sink_k, sink_v, k, v, cur, q_len, kdt):
        """Merge any of this call's rows that land in the sink range
        (positions [cur, cur+q_len) ∩ [0, sinks)) into the sink buffers
        — trace-safe at any ``cur``, a no-op once cur >= sinks."""
        sp = jnp.arange(self.sinks)
        covered = (sp >= cur) & (sp < cur + q_len)
        row = jnp.clip(sp - cur, 0, q_len - 1)
        sel = covered[None, :, None, None]
        sink_k.value = jnp.where(
            sel, jnp.take(k, row, axis=1).astype(kdt), sink_k.value)
        sink_v.value = jnp.where(
            sel, jnp.take(v, row, axis=1).astype(kdt), sink_v.value)

    def _slot_decode_step(self, x):
        """Per-slot KV-cache decode: every batch row has its own index.

        The continuous-batching engine (``serving.ServingEngine``) keeps B
        independent requests in flight; this is the same append-and-
        attend contract as ``_decode_step`` with three per-slot changes:
        the "index" cache variable is [B]; rows write via a per-row
        scatter at each slot's own position (out-of-range positions are
        DROPPED by jax scatter semantics — an overrun slot goes silently
        inert, the engine's budget accounting keeps that unobservable);
        and the causal mask compares against per-slot positions.  A
        refilled slot's stale rows are harmless: position p's row is
        always rewritten before any query can attend it (mask is
        kv_pos <= position and writes happen first).

        ``kv_cache_int8`` composes: rows store int8 with the shared
        per-(slot, position, kv_head) scale recipe
        (``_quantize_kv_rows``) in a [2, B, cache_len, kv_heads] scale
        var, dequant fused into the attention read — the serving
        engine's batch-1 prefill cache for int8 configs.
        """
        kv_heads = self.num_kv_heads or self.num_heads
        b, q_len, _ = x.shape

        q, k, v = self._qkv(x)

        cache_dtype = jnp.int8 if self.kv_cache_int8 else self.dtype
        cache_k = self.variable(
            "cache", "key_cache", jnp.zeros,
            (b, self.cache_len, kv_heads, self.head_dim), cache_dtype)
        cache_v = self.variable(
            "cache", "value_cache", jnp.zeros,
            (b, self.cache_len, kv_heads, self.head_dim), cache_dtype)
        if self.kv_cache_int8:
            kv_scales = self.variable(
                "cache", "kv_scales", jnp.zeros,
                (2, b, self.cache_len, kv_heads), jnp.float32)
        index = self.variable(
            "cache", "index", lambda: jnp.zeros((b,), jnp.int32))
        cur = index.value                                   # [B]
        positions = cur[:, None] + jnp.arange(q_len)        # [B, q]
        if self.use_rope:
            q = apply_rope(q, positions, base=self.rope_base,
                           scaling=self.rope_scaling)
            k = apply_rope(k, positions, base=self.rope_base,
                           scaling=self.rope_scaling)
        index.value = cur + q_len

        kdt = cache_k.value.dtype
        bidx = jnp.arange(b)[:, None]
        if self.kv_cache_int8:
            qk, sk = _quantize_kv_rows(k)
            qv, sv = _quantize_kv_rows(v)
            cache_k.value = cache_k.value.at[bidx, positions].set(qk)
            cache_v.value = cache_v.value.at[bidx, positions].set(qv)
            kv_scales.value = kv_scales.value.at[
                :, bidx, positions].set(jnp.stack([sk, sv]))
            kc = (cache_k.value.astype(self.dtype)
                  * kv_scales.value[0][..., None].astype(self.dtype))
            vc = (cache_v.value.astype(self.dtype)
                  * kv_scales.value[1][..., None].astype(self.dtype))
        else:
            cache_k.value = cache_k.value.at[bidx, positions].set(
                k.astype(kdt))
            cache_v.value = cache_v.value.at[bidx, positions].set(
                v.astype(kdt))
            kc, vc = cache_k.value, cache_v.value
        kv_pos = jnp.arange(self.cache_len)
        mask = kv_pos[None, None, :] <= positions[:, :, None]  # [B,q,C]
        return self._cache_attend(q, kc, vc,
                                  mask[:, None], kv_heads, b, q_len,
                                  x.shape[-1])

    def _paged_decode_step(self, x):
        """Per-slot decode over the PAGED pool: same append-and-attend
        contract as ``_slot_decode_step``, with the lane's contiguous
        cache strip replaced by a block-table indirection.

        Writes scatter each token's k/v row to ``pool[table[b, p //
        bs], p %% bs]`` (positions past the table width map to an
        out-of-range row and are DROPPED, the linear path's overrun
        rule; positions in table slots the engine zeroed land in the
        scratch block — garbage nobody reads).  Reads gather the lane's
        logical rows back into a [B, cache_len] view
        (``ops.pallas_kernels.paged_kv_gather`` — pure-jax on CPU, a
        scalar-prefetch block-copy kernel on TPU) and attend exactly as
        the linear path does: same mask, same positions, same einsum
        shapes, so outputs are bitwise-identical to the linear cache
        whenever the gathered bytes are (which the engine's block
        bookkeeping guarantees — pinned in tests/test_serving_paged.py).

        On TPU (or under ``TTD_FUSED_ATTN_INTERPRET=1``), the gather +
        attend pair is replaced by ONE fused kernel
        (``ops.pallas_kernels.paged_attention``) that computes
        flash-style decode attention directly through the block table
        — the dense per-lane KV view is never materialized, halving
        decode's HBM traffic.  ``TTD_NO_FUSED_ATTN=1`` restores the
        gather path (the byte-comparable A/B leg).  Sharded serving
        (an ambient mesh) keeps the gather path: GSPMD partitions the
        XLA gather, while the hand kernel is single-device.

        ``kv_cache_int8`` composes: pools store int8 rows quantized by
        the shared per-(row, kv_head) recipe, scales ride in a parallel
        [2, num_blocks, block_size, kv_heads] pool, and the dequant
        happens at read — fused into the kernel's block load, or into
        the gathered view's attention read on the A/B leg.
        """
        from tensorflow_train_distributed_tpu.ops import pallas_kernels \
            as pk

        kv_heads = self.num_kv_heads or self.num_heads
        b, q_len, _ = x.shape
        bs = self.kv_block_size
        nb = self.paged_kv_blocks
        n_blk = -(-self.cache_len // bs)

        q, k, v = self._qkv(x)

        cache_dtype = jnp.int8 if self.kv_cache_int8 else self.dtype
        cache_k = self.variable(
            "cache", "key_pool", jnp.zeros,
            (nb, bs, kv_heads, self.head_dim), cache_dtype)
        cache_v = self.variable(
            "cache", "value_pool", jnp.zeros,
            (nb, bs, kv_heads, self.head_dim), cache_dtype)
        if self.kv_cache_int8:
            kv_scales = self.variable(
                "cache", "kv_pool_scales", jnp.zeros,
                (2, nb, bs, kv_heads), jnp.float32)
        # All-zero init: every lane starts mapped to the scratch block,
        # so pre-insert garbage decode is self-contained by
        # construction.
        table = self.variable(
            "cache", "block_table", jnp.zeros, (b, n_blk), jnp.int32)
        index = self.variable(
            "cache", "index", lambda: jnp.zeros((b,), jnp.int32))
        cur = index.value                                   # [B]
        positions = cur[:, None] + jnp.arange(q_len)        # [B, q]
        if self.use_rope:
            q = apply_rope(q, positions, base=self.rope_base,
                           scaling=self.rope_scaling)
            k = apply_rope(k, positions, base=self.rope_base,
                           scaling=self.rope_scaling)
        index.value = cur + q_len

        kdt = cache_k.value.dtype
        if self.kv_cache_int8:
            k_store, sk = _quantize_kv_rows(k)
            v_store, sv = _quantize_kv_rows(v)
        else:
            k_store, v_store = k.astype(kdt), v.astype(kdt)
        # Physical destination row per (lane, token): the table lookup
        # CLIPS the block index (gather semantics would otherwise wrap)
        # and overrun positions are sent out of range so the scatter
        # drops them — an overrun lane goes silently inert, exactly the
        # linear path's rule.
        blk = jnp.clip(positions // bs, 0, n_blk - 1)
        phys = jnp.take_along_axis(table.value, blk, axis=1)  # [B, q]
        dest = jnp.where(positions < n_blk * bs,
                         phys * bs + positions % bs, nb * bs)
        flat_shape = (nb * bs, kv_heads, self.head_dim)
        cache_k.value = (
            cache_k.value.reshape(flat_shape)
            .at[dest.reshape(-1)]
            .set(k_store.reshape(-1, kv_heads, self.head_dim),
                 mode="drop")
            .reshape(nb, bs, kv_heads, self.head_dim))
        cache_v.value = (
            cache_v.value.reshape(flat_shape)
            .at[dest.reshape(-1)]
            .set(v_store.reshape(-1, kv_heads, self.head_dim),
                 mode="drop")
            .reshape(nb, bs, kv_heads, self.head_dim))
        if self.kv_cache_int8:
            sflat = kv_scales.value.reshape(2, nb * bs, kv_heads)
            sflat = sflat.at[:, dest.reshape(-1)].set(
                jnp.stack([sk, sv]).reshape(2, -1, kv_heads),
                mode="drop")
            kv_scales.value = sflat.reshape(2, nb, bs, kv_heads)

        if self._fused_paged_ok():
            out = pk.paged_attention(
                q, cache_k.value, cache_v.value, table.value, cur,
                k_scales=(kv_scales.value[0] if self.kv_cache_int8
                          else None),
                v_scales=(kv_scales.value[1] if self.kv_cache_int8
                          else None),
                cache_len=self.cache_len, use_pallas=True,
                interpret=pk.fused_attn_interpret())
            return self._attn_epilogue(out, b, q_len, x.shape[-1])

        kc = pk.paged_kv_gather(cache_k.value, table.value,
                                self.cache_len)
        vc = pk.paged_kv_gather(cache_v.value, table.value,
                                self.cache_len)
        if self.kv_cache_int8:
            ks = pk.paged_kv_gather(kv_scales.value[0][..., None],
                                    table.value, self.cache_len)
            vs = pk.paged_kv_gather(kv_scales.value[1][..., None],
                                    table.value, self.cache_len)
            kc = kc.astype(self.dtype) * ks.astype(self.dtype)
            vc = vc.astype(self.dtype) * vs.astype(self.dtype)
        kv_pos = jnp.arange(self.cache_len)
        mask = kv_pos[None, None, :] <= positions[:, :, None]  # [B,q,C]
        return self._cache_attend(q, kc, vc, mask[:, None], kv_heads, b,
                                  q_len, x.shape[-1])

    def _fused_paged_ok(self) -> bool:
        """Whether this paged decode should run the fused kernel: the
        env/backend decision (``use_fused_paged_attention``), vetoed
        under any >1-way ambient mesh — sharded serving keeps the XLA
        gather path so GSPMD can partition it (the hand kernel is
        single-device)."""
        from tensorflow_train_distributed_tpu.ops import pallas_kernels \
            as pk

        mesh = compat.get_abstract_mesh()
        if (mesh is not None and not mesh.empty
                and any(v > 1 for v in mesh.shape.values())):
            return False
        return pk.use_fused_paged_attention()

    def _cache_attend(self, q, kc, vc, mask, kv_heads, b, q_len, features):
        """Masked einsum attention of q over the cache buffers."""
        # Same logical sharding as the training path: under a tensor/fsdp
        # mesh the cache reads and attention activations shard over heads
        # rather than replicating (B, cache_len, H, D) per device.
        kv_ax = self._head_ax(kv_heads)
        kh = nn.with_logical_constraint(
            kc, ("batch", "length", kv_ax, "kv"))
        vh = nn.with_logical_constraint(
            vc, ("batch", "length", kv_ax, "kv"))
        if kv_heads != self.num_heads:
            rep = self.num_heads // kv_heads
            kh = jnp.repeat(kh, rep, axis=2)
            vh = jnp.repeat(vh, rep, axis=2)
        # [B, S, H, D] → [B, H, S, D].
        qh = q.transpose(0, 2, 1, 3)
        kh = kh.transpose(0, 2, 1, 3)
        vh = vh.transpose(0, 2, 1, 3)
        from tensorflow_train_distributed_tpu.ops.attention import (
            dot_product_attention,
        )

        out = dot_product_attention(qh, kh, vh, mask=mask)
        out = out.transpose(0, 2, 1, 3)
        return self._attn_epilogue(out, b, q_len, features)

    def _attn_epilogue(self, out, b, q_len, features):
        """Shared decode tail — constraint, head-merge, out-proj — for
        the gathered-attend path and the fused paged-attention kernel
        (one epilogue keeps the two paths' param use identical)."""
        out = nn.with_logical_constraint(
            out, ("batch", "length", self._head_ax(self.num_heads), "kv"))
        out = out.reshape(b, q_len, self.num_heads * self.head_dim)
        y = self._out_proj(out, features)
        return nn.with_logical_constraint(y, ("batch", "length", "embed"))

    def _rolling_block(self, x, q, k, v, cache_k, cache_v, cur, kv_heads,
                       b, q_len, fresh):
        """Multi-token call under the rolling cache, correct at ANY
        ``cur`` (first prefill, chunked prefill, speculative blocks).

        Ring invariant BEFORE the block: slot j holds position
        ``cur - w + ((j - cur) %% w)`` — the last w positions
        ``cur-w .. cur-1``, so rolling by ``-cur`` sorts the ring into
        positional order.  The block concatenates its fresh k/v after
        the unrolled ring, each query applies the causal+window+validity
        band over the w+q_len keys, and the last w rows of that concat
        re-roll into slot order as the new ring state."""
        w = self.window
        kdt = cache_k.value.dtype
        sinks = self.sinks
        if sinks:
            sink_k, sink_v = self._sink_buffers(b, kv_heads)
            # Merge this block's rows that land in the sink range first:
            # the sink COLUMNS below read the post-merge buffer, so a
            # block that decodes across the sink boundary sees its own
            # sink keys (trace-safe at any cur).
            self._write_sinks(sink_k, sink_v, k, v, cur, q_len, kdt)
        # First prefill (`fresh`: the cache collection was created THIS
        # call): the ring is knowably empty — skip the unroll/concat and
        # attend the block alone (a 128-token prompt must not pay a
        # w+128-key attention against w masked zeros).
        if fresh:
            kcat, vcat = k.astype(kdt), v.astype(kdt)
            kv_pos = jnp.arange(q_len)
            q_pos = jnp.arange(q_len)
            sink_cols = 0
        else:
            shift = jnp.mod(cur, w)
            ordered_k = jnp.roll(cache_k.value, -shift, axis=1)
            ordered_v = jnp.roll(cache_v.value, -shift, axis=1)
            kcat = jnp.concatenate([ordered_k, k.astype(kdt)], axis=1)
            vcat = jnp.concatenate([ordered_v, v.astype(kdt)], axis=1)
            kv_pos = cur - w + jnp.arange(w + q_len)      # global positions
            q_pos = cur + jnp.arange(q_len)
            sink_cols = sinks
            if sinks:
                kcat = jnp.concatenate([sink_k.value, kcat], axis=1)
                vcat = jnp.concatenate([sink_v.value, vcat], axis=1)
        band = ((kv_pos[None, :] >= 0)
                & (kv_pos[None, :] <= q_pos[:, None])
                & (q_pos[:, None] - kv_pos[None, :] < w))
        if fresh and sinks:
            # StreamingLLM keep-set during the first block: band OR sink
            # prefix (the block holds its own sink keys — no columns).
            band = band | ((kv_pos[None, :] < sinks)
                           & (kv_pos[None, :] <= q_pos[:, None]))
        if sink_cols:
            # Exclusivity at any cur: sink columns serve positions
            # < sinks (causally: si <= q_pos; unwritten rows are beyond
            # every q_pos), ring/block entries serve >= sinks.
            band = band & (kv_pos[None, :] >= sinks)
            sink_keep = (jnp.arange(sinks)[None, :] <= q_pos[:, None])
            keep = jnp.concatenate([sink_keep, band], axis=1)
        else:
            keep = band
        # New ring = last w positions written so far, re-packed so each
        # row with position p sits at slot p % w.  A fresh block shorter
        # than w writes positions 0..q_len-1 straight to slots 0..q_len-1
        # (untouched tail slots read as position < 0 → masked later).
        if fresh and q_len < w:
            cache_k.value = jax.lax.dynamic_update_slice(
                cache_k.value, kcat, (0, 0, 0, 0))
            cache_v.value = jax.lax.dynamic_update_slice(
                cache_v.value, vcat, (0, 0, 0, 0))
        else:
            end = jnp.mod(cur + q_len, w)
            cache_k.value = jnp.roll(kcat[:, -w:], end, axis=1)
            cache_v.value = jnp.roll(vcat[:, -w:], end, axis=1)
        return self._cache_attend(q, kcat, vcat, keep[None, None],
                                  kv_heads, b, q_len, x.shape[-1])


class MlpBlock(nn.Module):
    """Transformer FFN; gated (SwiGLU) when ``gated`` — Llama convention."""

    hidden: int
    dtype: Dtype = jnp.float32
    activation: Callable = nn.gelu
    gated: bool = False
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        # "mlp_hidden" checkpoint_name tags document the [B,S,ffn]
        # intermediates (identity unless a policy names them).  NOTE:
        # name-based EXCLUSION policies (save_anything_except_these_names)
        # do not work here — the pre-tag producer value stays saveable, so
        # the hiddens get saved anyway (measured: 6 stacked [L,B,S,ffn]
        # buffers in the v5e OOM dump).  The "no_ffn" remat policy
        # therefore wraps this whole module in an inner nothing-saveable
        # nn.remat at the call site (llama.DecoderBlock) instead.
        from jax.ad_checkpoint import checkpoint_name

        d = x.shape[-1]
        if self.gated:
            gate = checkpoint_name(
                dense(self.hidden, ("embed", "mlp"), use_bias=False,
                      dtype=self.dtype, name="wi_gate")(x), "mlp_hidden")
            up = checkpoint_name(
                dense(self.hidden, ("embed", "mlp"), use_bias=False,
                      dtype=self.dtype, name="wi_up")(x), "mlp_hidden")
            h = checkpoint_name(self.activation(gate) * up, "mlp_hidden")
        else:
            h = checkpoint_name(
                dense(self.hidden, ("embed", "mlp"), dtype=self.dtype,
                      name="wi")(x), "mlp_hidden")
            h = checkpoint_name(self.activation(h), "mlp_hidden")
        h = checkpoint_name(
            nn.with_logical_constraint(h, ("batch", "length", "mlp")),
            "mlp_hidden")
        if self.dropout_rate > 0 and not deterministic:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=deterministic)
        y = dense(d, ("mlp", "embed"), use_bias=not self.gated,
                  dtype=self.dtype, name="wo")(h)
        return nn.with_logical_constraint(y, ("batch", "length", "embed"))
