"""ResNet-50 (v1.5) for ImageNet — reference config[1].

The reference trains this under MultiWorkerMirroredStrategy with NCCL
allreduce and ``Model.fit`` (SURVEY.md §3.1) — the headline benchmark config
(BASELINE.md: ≥90% of MLPerf TPU-ref images/sec/chip).  TPU-first choices:

- NHWC layout + bfloat16 compute: XLA's conv tiling onto the MXU wants NHWC
  on TPU; params stay f32 (mixed-precision policy).
- v1.5 variant (stride 2 on the 3x3, not the 1x1) — the MLPerf reference
  architecture.
- BatchNorm over the global batch (sync-BN semantics fall out of global
  arrays; see ``vision_task``).
- conv kernels carry ("conv_in", "conv_out") logical axes so the tensor
  axis can shard output channels if a preset asks for it.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models.vision_task import VisionTask


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_filters: int = 64
    num_classes: int = 1000
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    # BN statistics over a spatially strided subset (1 = exact).  The
    # measured v5e step-time ceiling is BatchNorm HBM traffic, not conv
    # FLOPs (PROFILE.md: ~half the step in BN statistics/backward
    # reductions); stride 2 reads 1/4 of each activation for the mean/var
    # passes while normalizing the full tensor — at batch 256 the
    # estimate still pools >800k samples/channel in the first stage.
    # Running-stat/param names are unchanged, so checkpoints interchange
    # with the exact-BN variants.
    bn_stats_stride: int = 1
    # MLPerf TPU trick: 2x2 space-to-depth on the input ([N,224,224,3] →
    # [N,112,112,12]) turns the stride-2 7x7 stem conv into an equivalent
    # stride-1 4x4 conv with 12 input channels — 4x better MXU lane
    # utilization on the otherwise 3-channel-starved stem (~9% of step
    # time).  Mathematically identical model family: see
    # ``stem_kernel_to_s2d`` for the exact 7x7→4x4 kernel bijection.
    space_to_depth: bool = False


RESNET_PRESETS = {
    "resnet18": ResNetConfig(stage_sizes=(2, 2, 2, 2)),
    "resnet50": ResNetConfig(stage_sizes=(3, 4, 6, 3)),
    "resnet50_s2d": ResNetConfig(stage_sizes=(3, 4, 6, 3),
                                 space_to_depth=True),
    # s2d + subsampled BN statistics: the BN-traffic attack variant
    # (bench.py --configs can pit it against the exact-stats baselines).
    "resnet50_s2d_bnsub": ResNetConfig(stage_sizes=(3, 4, 6, 3),
                                       space_to_depth=True,
                                       bn_stats_stride=2),
    "resnet101": ResNetConfig(stage_sizes=(3, 4, 23, 3)),
    "resnet_tiny": ResNetConfig(stage_sizes=(1, 1), num_filters=8,
                                num_classes=10),
}


class SubsampledStatsBN(nn.Module):
    """BatchNorm whose TRAIN statistics come from a spatially strided
    subset of the activation (``x[:, ::s, ::s]``).

    The normalize-apply is algebraically refolded to one fused
    multiply-add (``x·w + b`` with w/b precomputed per channel in f32),
    and the mean/var reduction — the HBM-bound part of BN on TPU — reads
    only 1/s² of the tensor.  The batch dim is untouched, so dp/fsdp
    sharding and the global-batch sync-BN semantics (GSPMD reduces the
    sharded jnp.mean) are identical to ``nn.BatchNorm``.  Parameter and
    running-stat names match ``nn.BatchNorm`` ("scale"/"bias",
    "mean"/"var"), so checkpoints interchange between variants.

    ``stats_stride=1`` degenerates to exact one-pass (E[x²]−E[x]²) BN;
    the resnet builder still uses ``nn.BatchNorm`` there (flax's is the
    reference implementation this one is parity-tested against).
    """

    use_running_average: bool
    momentum: float
    epsilon: float
    dtype: object
    stats_stride: int = 2
    scale_init: object = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        import jax

        feat = x.shape[-1]
        scale = self.param("scale", self.scale_init, (feat,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (feat,),
                          jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32),
                                (feat,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), (feat,))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            s = self.stats_stride
            sub = x[:, ::s, ::s, :] if (x.ndim == 4 and s > 1) else x
            sub = sub.astype(jnp.float32)
            axes = tuple(range(sub.ndim - 1))
            mean = jnp.mean(sub, axes)
            # One-pass variance; clamped — subsampling can't make it
            # negative, but f32 cancellation can.
            var = jnp.maximum(
                jnp.mean(jnp.square(sub), axes) - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        inv = jax.lax.rsqrt(var + self.epsilon)
        w = (scale * inv).astype(self.dtype)
        b = (bias - mean * scale * inv).astype(self.dtype)
        return x.astype(self.dtype) * w + b


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """[N,H,W,C] → [N,H/b,W/b,b·b·C], channel-minor order (du, dv, c).

    Host pipelines should apply this before transfer (it is a pure data
    rearrangement); the model also applies it on the fly when handed raw
    3-channel input so both entry points work.
    """
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"spatial dims {h}x{w} not divisible by {block}")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def stem_kernel_to_s2d(w: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """Map a [7,7,C,F] stem kernel to the equivalent [4,4,b·b·C,F] kernel.

    With SAME padding (pad 3) and stride 2, output pixel i reads input rows
    2i-3..2i+3; on space-to-depth input those are transformed rows i-2..i+1
    — a 4-tap window.  Zero-padding the kernel to 8x8 (one leading zero
    row/col) aligns tap k to (m=du-block offset): k+1 = 2m+du, so the
    padded kernel reshapes exactly into the 4x4x(b·b·C) layout matching
    ``space_to_depth``'s channel order.
    """
    kh, kw, c, f = w.shape
    assert kh == 7 and kw == 7 and block == 2, "stem transform is 7x7/b=2"
    padded = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
    padded = padded.reshape(4, 2, 4, 2, c, f)        # (m, du, n, dv, c, f)
    padded = padded.transpose(0, 2, 1, 3, 4, 5)      # (m, n, du, dv, c, f)
    return padded.reshape(4, 4, block * block * c, f)


def _conv(features, kernel, strides=1, name=None, padding="SAME"):
    return nn.Conv(
        features, (kernel, kernel), strides=(strides, strides),
        padding=padding, use_bias=False,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
            (None, None, "conv_in", "conv_out"),
        ),
        name=name,
    )


def _norm_factory(cfg: ResNetConfig, train: bool, dtype):
    """The config's BN: flax's exact BatchNorm, or the strided-stats
    variant (same variable names — checkpoints interchange).

    Unnamed uses take flax's auto names for ``nn.BatchNorm``
    ("BatchNorm_0", ...) whichever implementation is active, so the tree
    structure is byte-compatible across ``bn_stats_stride`` settings.
    """
    if cfg.bn_stats_stride <= 1:
        return partial(
            nn.BatchNorm, use_running_average=not train,
            momentum=cfg.bn_momentum, epsilon=cfg.bn_epsilon, dtype=dtype)
    import itertools

    counter = itertools.count()
    base = partial(
        SubsampledStatsBN, use_running_average=not train,
        momentum=cfg.bn_momentum, epsilon=cfg.bn_epsilon,
        dtype=dtype, stats_stride=cfg.bn_stats_stride)

    def make(name: str = None, **kw):
        return base(name=name or f"BatchNorm_{next(counter)}", **kw)

    return make


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        norm = _norm_factory(self.config, train, x.dtype)
        residual = x
        y = _conv(self.filters, 1)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = _conv(self.filters, 3, self.strides)(y)  # v1.5: stride on 3x3
        y = norm()(y)
        y = nn.relu(y)
        y = _conv(self.filters * 4, 1)(y)
        # Zero-init the last BN scale (standard ResNet trick: each block
        # starts as identity, required to match reference loss curves).
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = _conv(self.filters * 4, 1, self.strides,
                             name="proj_conv")(x)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig = ResNetConfig()

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        cfg = self.config
        norm = _norm_factory(cfg, train, x.dtype)
        if cfg.space_to_depth:
            if x.shape[-1] == 3:  # raw input: transform on the fly
                x = space_to_depth(x)
            # Equivalent stride-1 4x4 stem on s2d input; padding (2,1)
            # from the tap-window derivation in stem_kernel_to_s2d.
            x = _conv(cfg.num_filters, 4, 1, name="stem_conv",
                      padding=((2, 1), (2, 1)))(x)
        else:
            x = _conv(cfg.num_filters, 7, 2, name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    filters=cfg.num_filters * 2**i,
                    strides=2 if j == 0 and i > 0 else 1,
                    config=cfg,
                )(x, train=train)
        x = nn.with_logical_constraint(x, ("batch", None, None, "conv_out"))
        x = x.mean(axis=(1, 2))  # global average pool
        x = nn.Dense(
            cfg.num_classes,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed", "vocab")),
            dtype=jnp.float32,
        )(x)
        return x


def make_task(config: ResNetConfig = RESNET_PRESETS["resnet50"],
              *, label_smoothing: float = 0.1,
              weight_decay: float = 1e-4) -> VisionTask:
    """MLPerf-style training task: label smoothing 0.1, weight decay 1e-4.

    ``uint8_mean_std`` enables the ship-raw-uint8 input contract
    (``imagenet_*_u8_*`` transforms): raw pixels normalize on DEVICE with
    the ImageNet constants — 4x less host→device transfer, measured +60%
    host records/sec (tools/bench_input.py) — bit-exact vs host-side
    normalization and bf16-policy-safe (VisionTask._prep_image).
    """
    from tensorflow_train_distributed_tpu.data.image import (
        MEAN_RGB, STDDEV_RGB,
    )

    return VisionTask(ResNet(config), label_smoothing=label_smoothing,
                      weight_decay=weight_decay,
                      uint8_mean_std=(MEAN_RGB * 255.0,
                                      STDDEV_RGB * 255.0))
