"""ResNet-50 (v1.5) for ImageNet — reference config[1].

The reference trains this under MultiWorkerMirroredStrategy with NCCL
allreduce and ``Model.fit`` (SURVEY.md §3.1) — the headline benchmark config
(BASELINE.md: ≥90% of MLPerf TPU-ref images/sec/chip).  TPU-first choices:

- NHWC layout + bfloat16 compute: XLA's conv tiling onto the MXU wants NHWC
  on TPU; params stay f32 (mixed-precision policy).
- v1.5 variant (stride 2 on the 3x3, not the 1x1) — the MLPerf reference
  architecture.
- BatchNorm over the global batch (sync-BN semantics fall out of global
  arrays; see ``vision_task``).
- conv kernels carry ("conv_in", "conv_out") logical axes so the tensor
  axis can shard output channels if a preset asks for it.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models.vision_task import VisionTask


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_filters: int = 64
    num_classes: int = 1000
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    # MLPerf TPU trick: 2x2 space-to-depth on the input ([N,224,224,3] →
    # [N,112,112,12]) turns the stride-2 7x7 stem conv into an equivalent
    # stride-1 4x4 conv with 12 input channels — 4x better MXU lane
    # utilization on the otherwise 3-channel-starved stem (~9% of step
    # time).  Mathematically identical model family: see
    # ``stem_kernel_to_s2d`` for the exact 7x7→4x4 kernel bijection.
    space_to_depth: bool = False


RESNET_PRESETS = {
    "resnet18": ResNetConfig(stage_sizes=(2, 2, 2, 2)),
    "resnet50": ResNetConfig(stage_sizes=(3, 4, 6, 3)),
    "resnet50_s2d": ResNetConfig(stage_sizes=(3, 4, 6, 3),
                                 space_to_depth=True),
    "resnet101": ResNetConfig(stage_sizes=(3, 4, 23, 3)),
    "resnet_tiny": ResNetConfig(stage_sizes=(1, 1), num_filters=8,
                                num_classes=10),
}


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """[N,H,W,C] → [N,H/b,W/b,b·b·C], channel-minor order (du, dv, c).

    Host pipelines should apply this before transfer (it is a pure data
    rearrangement); the model also applies it on the fly when handed raw
    3-channel input so both entry points work.
    """
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"spatial dims {h}x{w} not divisible by {block}")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def stem_kernel_to_s2d(w: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """Map a [7,7,C,F] stem kernel to the equivalent [4,4,b·b·C,F] kernel.

    With SAME padding (pad 3) and stride 2, output pixel i reads input rows
    2i-3..2i+3; on space-to-depth input those are transformed rows i-2..i+1
    — a 4-tap window.  Zero-padding the kernel to 8x8 (one leading zero
    row/col) aligns tap k to (m=du-block offset): k+1 = 2m+du, so the
    padded kernel reshapes exactly into the 4x4x(b·b·C) layout matching
    ``space_to_depth``'s channel order.
    """
    kh, kw, c, f = w.shape
    assert kh == 7 and kw == 7 and block == 2, "stem transform is 7x7/b=2"
    padded = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
    padded = padded.reshape(4, 2, 4, 2, c, f)        # (m, du, n, dv, c, f)
    padded = padded.transpose(0, 2, 1, 3, 4, 5)      # (m, n, du, dv, c, f)
    return padded.reshape(4, 4, block * block * c, f)


def _conv(features, kernel, strides=1, name=None, padding="SAME"):
    return nn.Conv(
        features, (kernel, kernel), strides=(strides, strides),
        padding=padding, use_bias=False,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
            (None, None, "conv_in", "conv_out"),
        ),
        name=name,
    )


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        norm = partial(
            nn.BatchNorm, use_running_average=not train,
            momentum=self.config.bn_momentum, epsilon=self.config.bn_epsilon,
            dtype=x.dtype,
        )
        residual = x
        y = _conv(self.filters, 1)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = _conv(self.filters, 3, self.strides)(y)  # v1.5: stride on 3x3
        y = norm()(y)
        y = nn.relu(y)
        y = _conv(self.filters * 4, 1)(y)
        # Zero-init the last BN scale (standard ResNet trick: each block
        # starts as identity, required to match reference loss curves).
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = _conv(self.filters * 4, 1, self.strides,
                             name="proj_conv")(x)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig = ResNetConfig()

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        cfg = self.config
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=cfg.bn_momentum, epsilon=cfg.bn_epsilon,
                       dtype=x.dtype)
        if cfg.space_to_depth:
            if x.shape[-1] == 3:  # raw input: transform on the fly
                x = space_to_depth(x)
            # Equivalent stride-1 4x4 stem on s2d input; padding (2,1)
            # from the tap-window derivation in stem_kernel_to_s2d.
            x = _conv(cfg.num_filters, 4, 1, name="stem_conv",
                      padding=((2, 1), (2, 1)))(x)
        else:
            x = _conv(cfg.num_filters, 7, 2, name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    filters=cfg.num_filters * 2**i,
                    strides=2 if j == 0 and i > 0 else 1,
                    config=cfg,
                )(x, train=train)
        x = nn.with_logical_constraint(x, ("batch", None, None, "conv_out"))
        x = x.mean(axis=(1, 2))  # global average pool
        x = nn.Dense(
            cfg.num_classes,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed", "vocab")),
            dtype=jnp.float32,
        )(x)
        return x


def make_task(config: ResNetConfig = RESNET_PRESETS["resnet50"],
              *, label_smoothing: float = 0.1,
              weight_decay: float = 1e-4) -> VisionTask:
    """MLPerf-style training task: label smoothing 0.1, weight decay 1e-4."""
    return VisionTask(ResNet(config), label_smoothing=label_smoothing,
                      weight_decay=weight_decay)
