"""Model zoo: the five reference configs, rebuilt TPU-first in flax.

Reference config list (BASELINE.json / SURVEY.md §2.1):

0. MNIST LeNet       — MirroredStrategy smoke test       → ``models.lenet``
1. ResNet-50/ImageNet — MultiWorkerMirroredStrategy/NCCL → ``models.resnet``
2. BERT-base MLM      — ParameterServerStrategy          → ``models.bert``
3. Transformer-big WMT — Horovod allreduce hook          → ``models.transformer``
4. Llama-2-7B SFT     — DTensor 2-D mesh (stretch)       → ``models.llama``

Every model: (a) annotates params/activations with logical axis names so one
definition serves every mesh preset; (b) provides a ``Task`` (init + loss)
for the Trainer; (c) ships preset configs including a tiny variant for CPU
tests.
"""

from tensorflow_train_distributed_tpu.models import registry  # noqa: F401
from tensorflow_train_distributed_tpu.models.registry import get_task  # noqa: F401
