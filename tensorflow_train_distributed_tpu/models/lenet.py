"""LeNet-5 for MNIST — reference config[0] (MirroredStrategy smoke test).

The reference runs this as its single-worker CPU/GPU sanity config; here it
is the dp-mesh sanity config (and the CI convergence canary).  Classic
LeNet-5 shape: conv5x5(6) → pool → conv5x5(16) → pool → 120 → 84 → 10.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn

from tensorflow_train_distributed_tpu.models.vision_task import VisionTask


@dataclasses.dataclass(frozen=True)
class LeNetConfig:
    num_classes: int = 10
    hidden: tuple[int, int] = (120, 84)


class LeNet(nn.Module):
    config: LeNetConfig = LeNetConfig()

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        del train  # no BN/dropout in classic LeNet
        x = nn.Conv(6, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for h in self.config.hidden:
            x = nn.Dense(h)(x)
            x = nn.relu(x)
        x = nn.with_logical_constraint(x, ("batch", None))
        return nn.Dense(self.config.num_classes)(x)


def make_task(config: LeNetConfig = LeNetConfig()) -> VisionTask:
    return VisionTask(LeNet(config))
