"""Autoregressive generation with a KV cache for the decoder family.

The reference is a training harness — its SFT config (SURVEY.md §2.1
config[4]) produces a model users then sample from elsewhere; here the
framework closes that loop natively.  TPU-first shape discipline: one
jitted function, static prompt/output lengths, ``lax.scan`` over decode
steps (no per-token dispatch), cache buffers donated between steps by XLA.

Two phases inside one jit:
- prefill: the whole prompt in a single call (``decode=True`` attention
  appends all prompt positions to the cache at once, causal via the index
  mask);
- step: ``lax.scan`` over single-token calls, greedy or temperature
  sampling.  Only the greedy-vs-sampling *branch* is static; the
  temperature value is traced, so a temperature sweep reuses one compile.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaModel,
)
from tensorflow_train_distributed_tpu.models.quant import (
    maybe_quant_variables,
    quantized_inference,
)
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    compile_site,
)


def _decode_model(config, cache_len: int, slot_decode: bool = False,
                  paged_kv_blocks: int = 0, kv_block_size: int = 0):
    """The decode-mode model for a decoder-family config: LlamaModel for
    LlamaConfig, MoeLmModel for MoeConfig (Mixtral-style) — one generate
    path serves every decoder family.  ``slot_decode`` selects the
    per-slot cache-index mode (serving.ServingEngine), and
    ``paged_kv_blocks``/``kv_block_size`` its paged-pool variant (the
    engine's block-table cache); this is the ONE family-dispatch point,
    shared by generate and the engine."""
    from tensorflow_train_distributed_tpu.models.moe import (
        MoeConfig,
        MoeLmModel,
    )

    cls = MoeLmModel if isinstance(config, MoeConfig) else LlamaModel
    return cls(config, decode=True, cache_len=cache_len,
               slot_decode=slot_decode,
               paged_kv_blocks=paged_kv_blocks,
               kv_block_size=kv_block_size)


def cast_floating(params, dtype):
    """Cast floating leaves to ``dtype`` (inference precision).

    Reads ``.dtype`` directly — ``jnp.asarray`` would round-trip every
    leaf through the device just to inspect it (26 GB of H2D at 7B).
    int8 kernels, ints, and non-array leaves pass through untouched.
    """
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params)


def has_lora_leaves(params) -> bool:
    """Whether a param tree carries unmerged LoRA adapters."""
    return any(
        getattr(p[-1], "key", None) in ("lora_a", "lora_b")
        for p, _ in jax.tree_util.tree_flatten_with_path(params)[0])


def validate_sampling(temperature, top_k, top_p) -> None:
    """Shared sampling-knob validation (generate + serving engine)."""
    if temperature < 0:
        raise ValueError(
            f"temperature must be >= 0, got {temperature} (negative "
            "values invert the distribution)")
    if temperature == 0.0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p filter a sampling distribution; set "
            "temperature > 0 (greedy argmax is unaffected by them)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def filter_logits(logits, *, temperature, top_k=None, top_p=None):
    """Temperature scale + top-k + nucleus filters over f32 ``logits``
    [..., V] — the sampling-distribution shaping shared by ``generate``
    and the serving engine (``top_k`` static: it sets the lax.top_k
    shape; ``temperature``/``top_p`` traced).  Filters compose k first,
    then p (the HF convention)."""
    logits = logits / temperature
    if top_k is not None and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        # Nucleus: keep the smallest prefix (by descending prob)
        # whose mass reaches p; the first token always survives.
        sorted_desc = -jnp.sort(-logits, axis=-1)
        cum = jnp.cumsum(jax.nn.softmax(sorted_desc), axis=-1)
        keep = cum - jax.nn.softmax(sorted_desc) <= top_p
        cutoff = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
            keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(config: LlamaConfig, params, prompt: jax.Array,
             max_new_tokens: int, *, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None,
             cast_params: bool = True,
             quant_scales=None) -> jax.Array:
    """Sample ``max_new_tokens`` continuations of ``prompt`` [B, S].

    ``temperature`` 0 → greedy argmax; > 0 → categorical sampling with
    ``rng`` (required).  ``top_k`` keeps only the k highest logits;
    ``top_p`` keeps the smallest nucleus of tokens whose probability mass
    reaches p (Holtzman et al.) — both filters apply after the
    temperature scale, compose (k first, then p — the HF convention), and
    require ``temperature > 0``.  Returns [B, S + max_new_tokens] ids.
    Prompt + new tokens must fit ``config.max_positions`` (the cache size).

    ``cast_params``: cast floating params to ``config.dtype`` before
    inference — a trained state carries f32 masters (26 GB at 7B), which
    inference neither needs nor fits on one chip; the compute path runs in
    ``config.dtype`` either way.  No-op for f32 configs.

    ``quant_scales``: the scale tree from ``models.quant.quantize_params``
    — pass it together with the int8 ``params`` that call returned and
    every Dense runs the fused weight-only-int8 path (decode weight
    traffic halves vs bf16).  int8 kernels are untouched by
    ``cast_params``.
    """
    b, prompt_len = prompt.shape
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got "
                         f"{max_new_tokens}")
    if max_new_tokens == 0:
        return prompt
    if prompt_len + max_new_tokens > config.max_positions:
        raise ValueError(
            f"prompt {prompt_len} + {max_new_tokens} new tokens exceeds "
            f"max_positions={config.max_positions} (the KV cache size)")
    validate_sampling(temperature, top_k, top_p)
    greedy = temperature == 0.0
    if not greedy and rng is None:
        raise ValueError("temperature sampling needs rng=")
    if rng is None:
        rng = jax.random.key(0)  # unused under greedy; keeps shapes static
    from tensorflow_train_distributed_tpu.models.lora import spec_of

    if spec_of(config) is not None and quant_scales is not None:
        raise ValueError(
            "int8 serving of a LoRA model needs the adapters folded in "
            "first: params = models.lora.merge_lora(params, spec), then "
            "quantize the merged tree with a lora=None config")
    if spec_of(config) is not None and has_lora_leaves(params):
        # Targets/rank must agree with the adapters actually present —
        # flax silently ignores unread leaves, so a narrower serving
        # spec would silently drop part of the fine-tune.
        from tensorflow_train_distributed_tpu.models.lora import (
            check_spec_matches,
        )

        check_spec_matches(params, spec_of(config))
    if spec_of(config) is None and has_lora_leaves(params):
        # flax apply would silently IGNORE the extra adapter leaves and
        # serve the un-adapted base — the fine-tuning vanishing without
        # a trace is the worst possible failure mode here.
        raise ValueError(
            "params carry unmerged LoRA adapters but config.lora is not "
            "set: either serve with the training config (lora=LoraSpec) "
            "or fold them in first via models.lora.merge_lora")
    from tensorflow_train_distributed_tpu.models.quant import (
        check_quant_pairing,
    )

    check_quant_pairing(params, quant_scales)
    if cast_params:
        params = cast_floating(params, config.dtype)
    # top_k is static (it sets the lax.top_k shape); top_p is a TRACED
    # scalar so a sampling sweep over p reuses one compiled graph.
    return _generate(config, max_new_tokens, greedy, top_k,
                     top_p is not None, params, prompt,
                     jnp.float32(temperature),
                     jnp.float32(1.0 if top_p is None else top_p), rng,
                     quant_scales)


@compile_site(buckets="exact (offline batch API: one compile per "
                      "prompt/output shape is the documented contract "
                      "— the serving engine is the bucketed path)",
              donates=(), statics=(),
              static_names=("config", "max_new_tokens", "greedy",
                            "top_k", "use_top_p"),
              max_compiles=None)
@partial(jax.jit, static_argnames=("config", "max_new_tokens", "greedy",
                                   "top_k", "use_top_p"))
def _generate(config: LlamaConfig, max_new_tokens: int, greedy: bool,
              top_k, use_top_p, params, prompt, temperature, top_p, rng,
              quant_scales=None):
    # Cache sized to the request, not max_positions: a 30-token generation
    # from a 4k-context config must not allocate (or attend over) 4k
    # cache rows per layer.
    model = _decode_model(config,
                          cache_len=prompt.shape[1] + max_new_tokens)

    def pick(logits, step_rng):
        logits = logits.astype(jnp.float32)
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits = filter_logits(logits, temperature=temperature,
                               top_k=top_k,
                               top_p=top_p if use_top_p else None)
        return jax.random.categorical(
            step_rng, logits, axis=-1).astype(prompt.dtype)

    base_vars = maybe_quant_variables(params, quant_scales)

    def infer_ctx():
        # LoRA configs serve unmerged adapters through the same
        # interceptor the training task uses; otherwise the (free when
        # inactive) int8 interceptor.  The two do not compose — generate
        # rejects that pairing up front.
        from tensorflow_train_distributed_tpu.models.lora import (
            maybe_lora_scope, spec_of,
        )

        return maybe_lora_scope(spec_of(config),
                                fallback=quantized_inference)

    # Prefill: whole prompt at once; next token comes from the last logit.
    with infer_ctx():
        logits, variables = model.apply(
            base_vars, prompt, mutable=["cache"])
    rngs = jax.random.split(rng, max_new_tokens)
    first = pick(logits[:, -1], rngs[0])

    def step(carry, step_rng):
        cache, tok = carry
        with infer_ctx():
            logits, updated = model.apply(
                dict(base_vars, cache=cache), tok[:, None],
                mutable=["cache"])
        nxt = pick(logits[:, -1], step_rng)
        return (updated["cache"], nxt), tok

    # first is token 1 of n; n-1 scan steps sample the rest.  toks collects
    # each step's *input* token, so toks = tokens 1..n-1 and `last` is n.
    (_, last), toks = jax.lax.scan(
        step, (variables["cache"], first), rngs[1:])
    out = jnp.moveaxis(toks, 0, 1)
    return jnp.concatenate([prompt, out, last[:, None]], axis=1)
