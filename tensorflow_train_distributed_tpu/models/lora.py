"""LoRA fine-tuning for the decoder family (low-rank adapters).

The reference's config[4] is Llama-2-7B SFT (SURVEY.md §2.1) — full
fine-tuning, whose optimizer state alone (14 B/param) busts a 16 GiB
chip at 7B.  LoRA (Hu et al., 2021) is the standard answer: freeze the
base weights, train rank-r deltas ``W + (alpha/r)·A·B`` on targeted
projections.  State shrinks to the adapters (~0.1% of params), and the
base can stay bf16 with no master copy.

TPU-first mechanics (zero model changes — the ``models.quant`` pattern):

- a flax method interceptor rewrites targeted ``nn.Dense``/
  ``nn.DenseGeneral`` calls to ``stop_gradient(base)(x) + scaling·
  (x@A)@B``.  ``stop_gradient`` on the kernel/bias means XLA never
  computes or stores base-weight gradients (the FLOP/memory win, not
  just an optimizer mask);
- adapters are ordinary flax params (``lora_a``/``lora_b`` beside each
  target kernel), so they ride the existing checkpoint/sharding/scan
  machinery — depth-scanned models stack them ``[L, in, r]`` exactly
  like their kernels;
- ``freeze_base(tx, ...)`` masks the optimizer so ONLY adapters get
  updates or optimizer state (embeddings/norms are frozen by mask;
  their grads are tiny);
- ``merge_lora(params, spec)`` folds the deltas into the kernels for
  serving/export (compose with ``models.quant`` AFTER merging).

Usage::

    spec = LoraSpec(rank=8, alpha=16.0)         # targets q,v by default
    cfg = dataclasses.replace(LLAMA_PRESETS["llama2_7b"], lora=spec)
    task = CausalLmTask(cfg)                     # applies under the scope
    tx = freeze_base(optax.adamw(1e-4))
    ...train as usual; checkpoint carries base + adapters...
    serving_params = merge_lora(state.params, spec)
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.traverse_util import flatten_dict, unflatten_dict


@dataclasses.dataclass(frozen=True)
class LoraSpec:
    """Hashable (lives inside frozen model configs under jit)."""

    rank: int = 8
    alpha: float = 16.0
    # Module NAMES to adapt (the attention/MLP Dense submodule names in
    # models.layers: query/key/value/out, wi_gate/wi_up/wo, lm_head).
    # The LoRA-paper default adapts q and v.
    targets: Tuple[str, ...] = ("query", "value")

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        if self.alpha <= 0:
            # alpha=0 zeroes the delta AND its gradients — with the base
            # frozen, nothing would train, silently.
            raise ValueError(f"alpha must be > 0, got {self.alpha}")
        if not self.targets:
            raise ValueError("targets must name at least one module")

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _make_interceptor(spec: LoraSpec):
    def interceptor(next_fn, args, kwargs, context):
        mdl = context.module
        if (context.method_name != "__call__"
                or not isinstance(mdl, (nn.Dense, nn.DenseGeneral))
                or mdl.name not in spec.targets):
            return next_fn(*args, **kwargs)
        if isinstance(mdl, nn.DenseGeneral) and not (
                isinstance(mdl.features, int) and mdl.axis == -1):
            raise ValueError(
                f"LoRA target {mdl.name!r} is a DenseGeneral beyond the "
                "Dense-shaped case (int features, axis=-1) — unsupported")
        (x,) = args
        dtype = mdl.dtype or x.dtype
        if mdl.has_variable("params", "kernel"):
            # Frozen base: stop_gradient at the READ, so XLA neither
            # computes nor stores dL/dW for it (dL/dx still flows).
            kernel = jax.lax.stop_gradient(
                mdl.get_variable("params", "kernel"))
            y = jax.lax.dot_general(
                x.astype(dtype), kernel.astype(dtype),
                (((x.ndim - 1,), (0,)), ((), ())))
            if mdl.use_bias:
                y = y + jax.lax.stop_gradient(
                    mdl.get_variable("params", "bias")).astype(dtype)
        else:
            # Init path: let the module create its own kernel/bias.
            y = next_fn(*args, **kwargs)
        in_dim = x.shape[-1]
        features = mdl.features  # int: asserted above for DenseGeneral
        # f32 masters for the trainable adapters; compute in the layer
        # dtype.  B starts at zero, so step 0 is exactly the base model.
        a = mdl.param("lora_a", nn.initializers.normal(0.02),
                      (in_dim, spec.rank), jnp.float32)
        b = mdl.param("lora_b", nn.initializers.zeros,
                      (spec.rank, features), jnp.float32)
        delta = (x.astype(dtype) @ a.astype(dtype)) @ b.astype(dtype)
        return y + delta * spec.scaling
    return interceptor


# The Dense submodule names models.layers actually uses — the universe
# --lora-targets / LoraSpec.targets can select from.  A typo here means
# NO adapters get created and a frozen-base run trains nothing, so
# callers validate eagerly (launch.py does at parse time).
KNOWN_TARGETS = frozenset({
    "query", "key", "value", "out",          # attention projections
    "wi_gate", "wi_up", "wo",                # MLP (llama is always gated)
    "lm_head",
})


def validate_targets(targets) -> tuple:
    """Strip + validate names against KNOWN_TARGETS; returns the tuple."""
    clean = tuple(t.strip() for t in targets if t.strip())
    unknown = [t for t in clean if t not in KNOWN_TARGETS]
    if unknown:
        raise ValueError(
            f"unknown LoRA target(s) {unknown}: valid names are "
            f"{sorted(KNOWN_TARGETS)} (the models.layers Dense submodule "
            "names — a non-matching name creates NO adapters and a "
            "frozen-base run would silently train nothing)")
    return clean


def lora_scope(spec: LoraSpec):
    """Context manager activating the adapters for init/apply."""
    return nn.intercept_methods(_make_interceptor(spec))


def spec_of(config):
    """The config's LoraSpec, or None — the ONE accessor for configs
    that may lack the field entirely (MoeConfig has no LoRA support; a
    scattered getattr at every touch point would mask typos)."""
    return getattr(config, "lora", None)


def maybe_lora_scope(spec, fallback=None):
    """``lora_scope(spec)`` when ``spec`` is set, else ``fallback()`` (or
    a nullcontext) — the one dispatch shared by the training task and
    ``generate`` so the two cannot drift."""
    if spec is not None:
        return lora_scope(spec)
    if fallback is not None:
        return fallback()
    import contextlib

    return contextlib.nullcontext()


SPEC_SIDECAR = "lora_spec.json"


def save_spec(checkpoint_dir, spec: LoraSpec) -> str:
    """Persist the spec beside the checkpoint (alpha is NOT recoverable
    from the weights, and a mismatched serve/merge silently corrupts) —
    the launcher writes this whenever LoRA training checkpoints."""
    import json
    import os

    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, SPEC_SIDECAR)
    with open(path, "w") as f:
        json.dump({"rank": spec.rank, "alpha": spec.alpha,
                   "targets": list(spec.targets)}, f)
    return path


def load_spec(checkpoint_dir):
    """The persisted LoraSpec, or None (non-LoRA checkpoint)."""
    import json
    import os

    path = os.path.join(checkpoint_dir, SPEC_SIDECAR)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        d = json.load(f)
    return LoraSpec(rank=int(d["rank"]), alpha=float(d["alpha"]),
                    targets=tuple(d["targets"]))


def check_spec_matches(params, spec: LoraSpec) -> None:
    """Raise unless the adapters IN the tree agree with ``spec`` on
    targets and rank.

    flax apply silently ignores params the model never reads, so a
    serving spec that targets fewer modules (or a different rank →
    shape-check failure only for matching names) than training would
    silently drop part of the fine-tune.  Alpha cannot be checked from
    weights — that is what the checkpoint sidecar (save_spec) is for.
    """
    flat = flatten_dict(_plain(params))
    seen_targets = {p[-2] for p in flat if p[-1] == "lora_a"}
    ranks = {v.shape[-1] for p, v in flat.items() if p[-1] == "lora_a"}
    if not seen_targets:
        raise ValueError("params carry no LoRA adapters but a LoraSpec "
                         "was given")
    if seen_targets != set(spec.targets):
        raise ValueError(
            f"LoRA spec/params mismatch: params carry adapters on "
            f"{sorted(seen_targets)} but the spec targets "
            f"{sorted(spec.targets)} — serving would silently drop or "
            "miss adapters (check --lora-targets against training, or "
            "use the checkpoint's lora_spec.json)")
    if ranks != {spec.rank}:
        raise ValueError(
            f"LoRA spec/params mismatch: adapter rank(s) {sorted(ranks)} "
            f"in params vs spec rank {spec.rank}")


def is_lora_param(path) -> bool:
    """``path``: a tuple of str keys (flatten_dict convention)."""
    return path[-1] in ("lora_a", "lora_b")


def _plain(tree):
    """Strip flax metadata boxes by value (raw ``model.init`` output;
    trained Trainer states arrive already unboxed)."""
    is_boxed = lambda x: isinstance(x, nn.meta.AxisMetadata)  # noqa: E731
    return jax.tree.map(lambda x: x.value if is_boxed(x) else x,
                        tree, is_leaf=is_boxed)


def lora_labels(params):
    """'lora' | 'frozen' label tree for ``optax.multi_transform``."""
    flat = flatten_dict(params)
    return unflatten_dict({
        p: ("lora" if is_lora_param(p) else "frozen") for p in flat})


def freeze_base(tx):
    """Wrap an optimizer so ONLY LoRA adapters receive updates — and
    only they get optimizer state (``multi_transform`` allocates the
    inner state per label, so frozen params carry no moments)."""
    import optax

    return optax.multi_transform(
        {"lora": tx, "frozen": optax.set_to_zero()}, lora_labels)


def count_lora_params(params) -> tuple[int, int]:
    """(trainable adapter params, total params)."""
    flat = flatten_dict(_plain(params))
    lora = sum(v.size for p, v in flat.items() if is_lora_param(p))
    total = sum(v.size for v in flat.values())
    return lora, total


def merge_lora(params, spec: LoraSpec):
    """Fold adapters into their kernels; drop the adapter leaves.

    Returns a plain base-model tree (loads into a no-LoRA config;
    quantize/export/serve from it).  Works for 2-D kernels and
    ``nn.scan``-stacked 3-D ones (adapters stack the same way).
    """
    flat = flatten_dict(_plain(params))
    out = {}
    merged = 0
    for path, w in flat.items():
        if is_lora_param(path):
            continue
        if path[-1] == "kernel":
            a = flat.get(path[:-1] + ("lora_a",))
            b = flat.get(path[:-1] + ("lora_b",))
            if a is not None and b is not None:
                delta = jnp.einsum("...ir,...ro->...io",
                                   a.astype(jnp.float32),
                                   b.astype(jnp.float32)) * spec.scaling
                w = (w.astype(jnp.float32) + delta).astype(w.dtype)
                merged += 1
        out[path] = w
    if merged == 0:
        raise ValueError(
            "no (lora_a, lora_b) pairs found beside any kernel — was "
            "this tree trained under lora_scope/a lora= config?")
    return unflatten_dict(out)
