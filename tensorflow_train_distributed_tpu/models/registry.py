"""Model/task registry — name → Task factory + dataset pairing.

The lookup table behind the CLI's ``--config`` flag (the reference
launcher's per-model dispatch, SURVEY.md §2.1).  Tiny variants exist for
every family so each model's full path runs on CPU test meshes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

_REGISTRY: dict[str, dict[str, Any]] = {}


def register(name: str, *, task_factory: Callable, dataset: str,
             dataset_kwargs: dict | None = None, strategy: str = "dp",
             global_batch_size: int = 32, learning_rate: float = 1e-3,
             lr_schedule: str = "constant", warmup_ratio: float = 0.0,
             grad_clip_norm: float | None = None):
    _REGISTRY[name] = dict(
        task_factory=task_factory, dataset=dataset,
        dataset_kwargs=dataset_kwargs or {}, strategy=strategy,
        global_batch_size=global_batch_size, learning_rate=learning_rate,
        lr_schedule=lr_schedule, warmup_ratio=warmup_ratio,
        grad_clip_norm=grad_clip_norm,
    )


def get_task(name: str):
    return get_entry(name)["task_factory"]()


def get_entry(name: str) -> dict[str, Any]:
    if name not in _REGISTRY:
        raise ValueError(
            f"Unknown config {name!r}; available: {sorted(_REGISTRY)}")
    return dict(_REGISTRY[name])


def available() -> list[str]:
    return sorted(_REGISTRY)


def _setup():
    from tensorflow_train_distributed_tpu.models import (
        bert, lenet, llama, moe, resnet, transformer, vit,
    )

    # Reference config[0]: MNIST LeNet (MirroredStrategy smoke test).
    register("mnist", task_factory=lenet.make_task, dataset="mnist",
             strategy="dp", global_batch_size=128, learning_rate=1e-3)
    # Reference config[1]: ResNet-50 / ImageNet (MWMS + NCCL → dp over ICI).
    register("resnet50_imagenet",
             task_factory=lambda: resnet.make_task(
                 resnet.RESNET_PRESETS["resnet50"]),
             dataset="imagenet", strategy="dp", global_batch_size=1024,
             learning_rate=0.4, lr_schedule="resnet_steps",
             warmup_ratio=0.05)
    # MXU-optimized variant: 2x2 space-to-depth stem (host-side transform
    # in the dataset, stride-1 4x4 stem conv in the model).
    register("resnet50_imagenet_s2d",
             task_factory=lambda: resnet.make_task(
                 resnet.RESNET_PRESETS["resnet50_s2d"]),
             dataset="imagenet", dataset_kwargs=dict(space_to_depth=True),
             strategy="dp", global_batch_size=1024,
             learning_rate=0.4, lr_schedule="resnet_steps",
             warmup_ratio=0.05)
    # s2d + 2-strided BN statistics (the BN-HBM-traffic attack variant,
    # PROFILE.md): CLI-trainable so its convergence can be certified
    # against resnet50_imagenet_s2d before it claims the headline.
    register("resnet50_imagenet_s2d_bnsub",
             task_factory=lambda: resnet.make_task(
                 resnet.RESNET_PRESETS["resnet50_s2d_bnsub"]),
             dataset="imagenet", dataset_kwargs=dict(space_to_depth=True),
             strategy="dp", global_batch_size=1024,
             learning_rate=0.4, lr_schedule="resnet_steps",
             warmup_ratio=0.05)
    register("resnet_tiny",
             task_factory=lambda: resnet.make_task(
                 resnet.RESNET_PRESETS["resnet_tiny"],
                 label_smoothing=0.0, weight_decay=0.0),
             dataset="imagenet",
             dataset_kwargs=dict(num_classes=10, image_size=32),
             strategy="dp", global_batch_size=64, learning_rate=1e-3)
    # ViT (beyond the reference's vision list): same ImageNet pipeline
    # as ResNet, transformer encoder stack; AdamW-style training
    # (warmup+cosine, grad clip 1.0 — the AugReg recipe shape).
    register("vit_b16_imagenet",
             task_factory=lambda: vit.make_task(
                 vit.VIT_PRESETS["vit_b16"]),
             dataset="imagenet", strategy="dp", global_batch_size=1024,
             learning_rate=3e-3, lr_schedule="warmup_cosine",
             warmup_ratio=0.03, grad_clip_norm=1.0)
    register("vit_tiny",
             task_factory=lambda: vit.make_task(
                 vit.VIT_PRESETS["vit_tiny"],
                 label_smoothing=0.0),
             dataset="imagenet",
             dataset_kwargs=dict(num_classes=10, image_size=32),
             strategy="dp", global_batch_size=64, learning_rate=1e-3)
    # Reference config[2]: BERT-base MLM (PS strategy → SPMD dp_tp).
    register("bert_base_mlm",
             task_factory=lambda: bert.make_task(
                 bert.BERT_PRESETS["bert_base"]),
             dataset="mlm", strategy="dp", global_batch_size=256,
             learning_rate=1e-4, lr_schedule="warmup_linear",
             warmup_ratio=0.1,
             # BERT pretrain convention (Devlin et al. / NVIDIA refs):
             # global-norm clip 1.0.
             grad_clip_norm=1.0)
    register("bert_tiny_mlm",
             task_factory=lambda: bert.make_task(
                 bert.BERT_PRESETS["bert_tiny"]),
             dataset="mlm",
             dataset_kwargs=dict(vocab_size=256, seq_len=64),
             strategy="dp", global_batch_size=32, learning_rate=1e-3)
    # Reference config[3]: Transformer-big WMT (Horovod hook → dp).
    register("transformer_big_wmt",
             task_factory=lambda: transformer.make_task(
                 transformer.TRANSFORMER_PRESETS["transformer_big"]),
             dataset="wmt", strategy="dp", global_batch_size=512,
             learning_rate=2.0, lr_schedule="noam", warmup_ratio=0.0)
    register("transformer_tiny_wmt",
             task_factory=lambda: transformer.make_task(
                 transformer.TRANSFORMER_PRESETS["transformer_tiny"]),
             dataset="wmt",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp", global_batch_size=32, learning_rate=1e-3)
    # Reference config[4]: Llama-2-7B SFT (DTensor 2-D mesh).  fsdp_tp,
    # not dp_tp: pure dp×tp replicates the ~79 GiB params+adam state over
    # the data axis (~19 GiB/device at tensor=4 — over v5e HBM), while
    # fsdp shards it (AOT-validated in
    # tests/test_models.py::TestLlama7bMemoryBudget).
    register("llama2_7b_sft",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["llama2_7b"]),
             dataset="lm", strategy="fsdp_tp", global_batch_size=64,
             learning_rate=2e-5, lr_schedule="warmup_cosine",
             warmup_ratio=0.03,
             # Llama-2 training convention: global-norm clip 1.0.
             grad_clip_norm=1.0)
    # Llama-3.1-8B SFT (GQA + llama3 rope scaling; --init-from-hf).
    register("llama31_8b_sft",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["llama31_8b"]),
             dataset="lm", strategy="fsdp_tp", global_batch_size=64,
             learning_rate=2e-5, lr_schedule="warmup_cosine",
             warmup_ratio=0.03, grad_clip_norm=1.0)
    # Gemma-1 SFT entries (decoupled head_dim, embed scaling, GeGLU,
    # zero-centered norms — import_hf maps checkpoints exactly).
    register("gemma_2b_sft",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["gemma_2b"]),
             dataset="lm", strategy="dp", global_batch_size=64,
             learning_rate=2e-5, lr_schedule="warmup_cosine",
             warmup_ratio=0.03, grad_clip_norm=1.0)
    register("gemma_7b_sft",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["gemma_7b"]),
             dataset="lm", strategy="fsdp_tp", global_batch_size=64,
             learning_rate=2e-5, lr_schedule="warmup_cosine",
             warmup_ratio=0.03, grad_clip_norm=1.0)
    # Qwen2.5-7B SFT (qkv-bias dense family; import_hf maps the
    # checkpoints exactly — model_type "qwen2").
    register("qwen25_7b_sft",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["qwen25_7b"]),
             dataset="lm", strategy="fsdp_tp", global_batch_size=64,
             learning_rate=2e-5, lr_schedule="warmup_cosine",
             warmup_ratio=0.03, grad_clip_norm=1.0)
    # The single-chip benchmark flagship (bench_lm / __graft_entry__):
    # GPT-2-small-class decoder, trainable through the CLI on one chip.
    register("llama_125m_lm",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["llama_125m"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=32_000, seq_len=2048),
             strategy="dp", global_batch_size=8,
             learning_rate=3e-4, lr_schedule="warmup_cosine",
             warmup_ratio=0.01, grad_clip_norm=1.0)
    # Mid-size decoder (GPT-medium-class): the single-chip MFU point
    # above 125m; no_ffn remat is what makes b4×2048 fit 16 GiB.
    register("llama_350m_lm",
             task_factory=lambda: llama.make_task(dataclasses.replace(
                 llama.LLAMA_PRESETS["llama_350m"],
                 remat=True, remat_policy="no_ffn")),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=32_000, seq_len=2048),
             strategy="dp", global_batch_size=4,
             learning_rate=3e-4, lr_schedule="warmup_cosine",
             warmup_ratio=0.01, grad_clip_norm=1.0)
    # Mistral-family flagship: GQA + sliding-window attention (O(S·w)
    # chunked path) over 32k positions; same weight layout as llama so
    # --init-from-hf imports real Mistral checkpoints.
    register("mistral_7b_lm",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["mistral_7b"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=32_000, seq_len=8192),
             strategy="fsdp_tp", global_batch_size=8,
             learning_rate=3e-4, lr_schedule="warmup_cosine",
             warmup_ratio=0.01, grad_clip_norm=1.0)
    # CPU-trainable windowed-family canary (CI-sized mistral shape).
    register("mistral_tiny_lm",
             task_factory=lambda: llama.make_task(
                 dataclasses.replace(
                     llama.LLAMA_PRESETS["llama_tiny"],
                     sliding_window=16, attention_sinks=4)),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=64),
             strategy="dp", global_batch_size=16, learning_rate=1e-3)
    # Beyond the reference (it has no MoE): expert-parallel decoder LM.
    register("mixtral_8x7b",
             task_factory=lambda: moe.make_task(
                 moe.MOE_PRESETS["mixtral_8x7b"]),
             dataset="lm", strategy="dp_ep", global_batch_size=64,
             learning_rate=1e-4)
    register("moe_tiny_lm",
             task_factory=lambda: moe.make_task(
                 moe.MOE_PRESETS["moe_tiny"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp_ep", global_batch_size=16, learning_rate=1e-3)
    # Qwen1.5-MoE-A2.7B flagship (gated shared expert + 60-expert
    # fine-grained routing): --init-from-hf a local checkpoint.
    register("qwen15_moe_a27b",
             task_factory=lambda: moe.make_task(
                 moe.MOE_PRESETS["qwen15_moe_a27b"]),
             dataset="lm", strategy="dp_ep", global_batch_size=64,
             learning_rate=1e-4)
    # Tiny full-Qwen-convention shape (the CLI import test fixture).
    register("qwen_moe_tiny_lm",
             task_factory=lambda: moe.make_task(
                 moe.MOE_PRESETS["qwen_moe_tiny"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp_ep", global_batch_size=16, learning_rate=1e-3)
    # DeepSeek/Qwen-MoE-style shared expert beside the routed ones
    # (MoeConfig.shared_expert_size) — trains/serves through every MoE
    # path; the shared branch is an ordinary dense FFN.
    register("moe_tiny_shared_lm",
             task_factory=lambda: moe.make_task(
                 moe.MOE_PRESETS["moe_tiny_shared"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp_ep", global_batch_size=16, learning_rate=1e-3)
    # Dropless (megablox grouped-matmul) dispatch variant: same params/
    # data/seed as moe_tiny_lm, only the expert data movement differs —
    # the convergence-certification pair for MoeConfig.dispatch="gmm"
    # (profiles/convergence/).  dp strategy: gmm is the single-shard
    # formulation; expert-sharded meshes keep the dense dispatch.
    register("moe_tiny_lm_gmm",
             task_factory=lambda: moe.make_task(
                 dataclasses.replace(moe.MOE_PRESETS["moe_tiny"],
                                     dispatch="gmm")),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp", global_batch_size=16, learning_rate=1e-3)
    register("llama_tiny_sft",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["llama_tiny"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp_tp", global_batch_size=16, learning_rate=1e-3)
    # Pipeline parallelism end-to-end: --strategy=dp_pp drives the GPipe
    # schedule (parallel.pipeline) for the scanned decoder stack; the same
    # config under --strategy=dp runs the plain depth scan with identical
    # numerics.
    register("llama_tiny_pp",
             task_factory=lambda: llama.make_task(
                 llama.LLAMA_PRESETS["llama_tiny_pp"]),
             dataset="lm",
             dataset_kwargs=dict(vocab_size=256, seq_len=32),
             strategy="dp_pp", global_batch_size=16, learning_rate=1e-3)


_setup()
