"""Vision Transformer (ViT) for image classification.

Beyond the reference's model list (its vision configs are LeNet and
ResNet-50, SURVEY.md §2.1) — added because the zoo's encoder stack
(`layers.MultiHeadAttention` + `MlpBlock`, the same modules BERT and the
WMT transformer run on) plus the ImageNet input pipeline make ViT nearly
free, and it is the standard vision architecture a reference user would
expect from a modern framework.  TPU-first choices:

- Patch embedding as a stride-``patch`` conv: one big matmul per image
  on the MXU (224/16 → 196 patches), no gather/reshape shuffle.
- Pre-LN blocks (ViT convention) reusing the shared attention kernel —
  so ViT inherits flash attention on TPU, Megatron-style TP via the
  ("embed", "heads") kernel axes, and the mixed-precision policy.
- Learned position embeddings sized to the config's grid; bilinear
  resize at load time is a checkpoint-tool concern, not a model one.
- Classification via mean-pool ("gap", default — one less special
  token keeps the sequence length a clean 4·k for the MXU) or a CLS
  token ("cls", the paper's variant) — both CLI-selectable.

VisionTask provides the softmax-CE + label-smoothing + top-5 task
wrapper (the reference harness's per-model ``train_step`` equivalent),
so ViT composes with every data path (JPEG ingestion, ship-raw-uint8,
packing-free image batches) and every mesh strategy the CLI offers.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models import layers as L
from tensorflow_train_distributed_tpu.models.vision_task import VisionTask


@dataclasses.dataclass(frozen=True)
class VitConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dropout_rate: float = 0.0
    pooling: str = "gap"  # "gap" (mean-pool) | "cls" (class token)
    dtype: object = jnp.float32
    layer_norm_eps: float = 1e-6
    # Activation checkpointing per encoder layer (nn.remat).
    remat: bool = False

    @property
    def num_patches(self) -> int:
        side, rem = divmod(self.image_size, self.patch_size)
        if rem:
            raise ValueError(
                f"image_size {self.image_size} not divisible by "
                f"patch_size {self.patch_size}")
        return side * side


VIT_PRESETS = {
    # Standard sizes (ViT paper / AugReg naming).
    "vit_b16": VitConfig(),
    "vit_s16": VitConfig(hidden_size=384, num_layers=12, num_heads=6,
                         mlp_dim=1536),
    "vit_l16": VitConfig(hidden_size=1024, num_layers=24, num_heads=16,
                         mlp_dim=4096),
    # CPU-mesh test config.
    "vit_tiny": VitConfig(image_size=32, patch_size=8, hidden_size=32,
                          num_layers=2, num_heads=2, mlp_dim=64,
                          num_classes=10),
}


class VitEncoderLayer(nn.Module):
    """Pre-LN transformer block (LN → attn → +x; LN → MLP → +x)."""

    config: VitConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype, epsilon=cfg.layer_norm_eps,
                         name="attn_ln")(x)
        h = L.MultiHeadAttention(
            num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            dtype=cfg.dtype,
            dropout_rate=cfg.dropout_rate,
            use_bias=True,  # ViT convention: qkv/out projections biased
            name="attention",
        )(h, deterministic=deterministic)
        x = x + h
        h = nn.LayerNorm(dtype=cfg.dtype, epsilon=cfg.layer_norm_eps,
                         name="mlp_ln")(x)
        h = L.MlpBlock(
            hidden=cfg.mlp_dim, dtype=cfg.dtype,
            dropout_rate=cfg.dropout_rate, name="mlp",
            activation=nn.gelu,
        )(h, deterministic=deterministic)
        return x + h


class VisionTransformer(nn.Module):
    config: VitConfig = VitConfig()

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        deterministic = not train
        # Patch embedding: stride-P conv == per-patch linear projection,
        # lowered by XLA to one [B·N, P²·C]×[P²·C, H] MXU matmul.
        x = nn.Conv(
            cfg.hidden_size,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID", dtype=cfg.dtype, name="patch_embed",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(),
                (None, None, "conv_in", "embed")),
        )(x)
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.hidden_size)  # [B, N_patches, H]
        seq = cfg.num_patches
        if x.shape[1] != seq:
            raise ValueError(
                f"got {x.shape[1]} patches for input {x.shape}, config "
                f"expects {seq} ({cfg.image_size}px / {cfg.patch_size}px "
                f"grid); check the dataset image_size")
        if cfg.pooling == "cls":
            cls = self.param(
                "cls_token",
                nn.with_logical_partitioning(
                    nn.initializers.zeros, (None, None, "embed")),
                (1, 1, cfg.hidden_size))
            x = jnp.concatenate(
                [jnp.tile(cls.astype(cfg.dtype), (b, 1, 1)), x], axis=1)
            seq += 1
        pos = self.param(
            "pos_embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, "embed")),
            (seq, cfg.hidden_size))
        x = x + pos[None].astype(cfg.dtype)
        if cfg.dropout_rate:
            x = nn.Dropout(cfg.dropout_rate, deterministic=deterministic,
                           name="embed_dropout")(x)
        x = nn.with_logical_constraint(x, ("batch", "length", "embed"))
        layer_cls = (nn.remat(VitEncoderLayer, static_argnums=(2,))
                     if cfg.remat else VitEncoderLayer)
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, epsilon=cfg.layer_norm_eps,
                         name="final_ln")(x)
        x = x[:, 0] if cfg.pooling == "cls" else x.mean(axis=1)
        logits = nn.Dense(
            cfg.num_classes, dtype=cfg.dtype, name="head",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed", "vocab")),
        )(x)
        return nn.with_logical_constraint(logits, ("batch", "vocab"))


def make_task(config: VitConfig = VIT_PRESETS["vit_b16"], *,
              label_smoothing: float = 0.1,
              weight_decay: float = 0.0) -> VisionTask:
    """ViT task (AdamW-style decoupled decay belongs in the optimizer,
    so ``weight_decay`` defaults off here unlike ResNet's L2)."""
    from tensorflow_train_distributed_tpu.data.image import (
        MEAN_RGB, STDDEV_RGB,
    )
    return VisionTask(VisionTransformer(config),
                      label_smoothing=label_smoothing,
                      weight_decay=weight_decay,
                      uint8_mean_std=(MEAN_RGB * 255.0,
                                      STDDEV_RGB * 255.0))
