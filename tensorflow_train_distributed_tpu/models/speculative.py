"""Speculative decoding: a small draft model proposes, the target
verifies — exact greedy output at a fraction of the target steps.

Beyond the reference (a training harness): the standard serving-latency
lever for autoregressive decode (Leviathan et al., 2023, greedy case).
Each round the draft generates ``k`` tokens autoregressively (cheap),
then the target scores the whole ``k+1``-token block in ONE forward pass
(decode is weight-bandwidth-bound, so a k+1-token call costs about the
same HBM traffic as a 1-token call).  The emitted sequence is PROVABLY
identical to the target's own greedy decode: accepted drafts are exactly
the target's argmaxes, and the first disagreement is replaced by the
target's choice.

TPU-first mechanics, one jit end to end:

- fixed shapes everywhere: ``k`` is static, each round emits between 1
  and k+1 tokens into a fixed ``[max_new + k + 1]`` buffer (garbage tail
  of a round is overwritten by the next round's fixed-width write);
- ``lax.while_loop`` over rounds (1+ tokens per round ⇒ terminates);
- cache rollback is an INDEX RESET: the linear KV cache masks rows at
  ``kv_pos <= position`` and overwrites stale rows in place, so
  rejected speculation costs nothing to undo.  (Rolling window caches
  are destructive on overwrite — sliding-window configs are rejected.)
- the draft runs ``k+1`` steps (the last append-only), so both caches
  hold identical row sets and roll back by the same rule.

Batch must be 1: acceptance length varies per sequence, and the KV
cache keeps ONE index per batch (speculation is a small-batch latency
optimization; larger batches should just batch normally).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    compile_site,
)
from tensorflow_train_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaModel,
)


def _reject_config(name: str, cfg: LlamaConfig):
    if not isinstance(cfg, LlamaConfig):
        raise ValueError(
            f"{name} config is {type(cfg).__name__}; speculative decode "
            "supports the Llama family only (MoE decode serves through "
            "generate(), but draft/verify rollback is untested there)")
    if getattr(cfg, "sliding_window", None) is not None:
        raise ValueError(
            f"{name} config uses sliding_window={cfg.sliding_window}: "
            "the rolling KV ring overwrites rows destructively, so "
            "speculative rollback (an index reset) is unsound — use "
            "full-attention configs")
    if getattr(cfg, "lora", None) is not None:
        raise ValueError(
            f"{name} config carries LoRA adapters; merge them first "
            "(models.lora.merge_lora) — speculative decode serves plain "
            "base trees")


def _accept_count(ok):
    """Leading-True count per row of ``ok`` [B, k] — the appended zero
    column makes argmin return k when every flag is True.  THE shared
    accepted-count rule for greedy and sampled acceptance."""
    b = ok.shape[0]
    return jnp.argmin(jnp.concatenate(
        [ok.astype(jnp.int32), jnp.zeros((b, 1), jnp.int32)],
        axis=1), axis=1)                                         # [B]


def _assemble_emit(d_block, a, final):
    """Emit layout shared by both acceptance rules: row i carries
    d_0..d_{a-1}, then ``final`` at position a, zero-padding beyond."""
    k = d_block.shape[1]
    idx = jnp.arange(k + 1)[None, :]
    # Explicit zero column (not ``zeros_like(d_block[:, :1])``): at
    # k=0 — the serving engine's plain-decode depth bucket — d_block
    # is [B, 0] and slicing it yields another empty column.
    d_pad = jnp.concatenate(
        [d_block, jnp.zeros((d_block.shape[0], 1), d_block.dtype)],
        axis=1)
    return jnp.where(idx < a[:, None], d_pad,
                     jnp.where(idx == a[:, None], final[:, None], 0))


def accept_block(d_block, preds):
    """Batched accept-prefix computation (Leviathan greedy rule).

    ``d_block`` [B, k] draft proposals, ``preds`` [B, k+1] the target's
    greedy choices over the verify block.  Returns ``(emit [B, k+1],
    emitted [B], accepted [B], next_tok [B])``: per row, the leading
    ``a`` drafts that match the target are emitted followed by the
    target's own pick at the first disagreement (the "bonus"); rows
    beyond ``emitted`` are zero-padding.  Shared by the batch-1 library
    path and the serving engine's all-slots rounds; the accepted-count
    and emit-assembly tricks live in ``_accept_count``/``_assemble_emit``
    so greedy and sampled acceptance cannot desynchronize.
    """
    k = d_block.shape[1]
    a = _accept_count(d_block == preds[:, :k])
    emitted = a + 1
    bonus = jnp.take_along_axis(preds, a[:, None], axis=1)[:, 0]  # [B]
    emit = _assemble_emit(d_block, a, bonus)
    return emit.astype(d_block.dtype), emitted, a, bonus


def sampled_accept(d_block, q, p, us, final_keys):
    """Rejection-sampling acceptance (Leviathan et al. generalized from
    the greedy prefix-match rule), batched — THE shared law for the
    serving engine's all-slot rounds and the batch-1 library path.

    ``d_block`` [B, k] draft samples drawn from ``q`` [B, k, V] (the
    draft's filtered/softmaxed proposal distributions); ``p``
    [B, k+1, V] the target's filtered/softmaxed distributions over the
    verify block; ``us`` [B, k] acceptance uniforms; ``final_keys``
    [B] rng keys for the residual/bonus draw.  Accept draft ``x_i``
    with probability min(1, p_i(x_i)/q_i(x_i)); at the first rejection
    draw from the residual norm(max(p_i − q_i, 0)); if all k survive,
    draw the bonus from ``p_k`` (q zero-padded makes that one formula —
    the residual of p−0 is p).  Emitted tokens are distributed EXACTLY
    as sampling from ``p`` — speculation changes latency, not the law.

    Returns ``(emit [B, k+1], emitted [B], accepted [B], final [B])``
    with the same emit layout as ``accept_block``.
    """
    k = d_block.shape[1]
    gather = lambda dist, ids: jnp.take_along_axis(
        dist, ids[..., None].astype(jnp.int32), axis=2)[..., 0]
    px = gather(p[:, :k], d_block)             # [B, k]
    qx = gather(q, d_block)                    # [B, k]
    a = _accept_count(us * qx < px)  # u < p/q without dividing
    emitted = a + 1
    q_pad = jnp.concatenate([q, jnp.zeros_like(p[:, :1])], axis=1)
    p_at = jnp.take_along_axis(p, a[:, None, None], axis=1)[:, 0]
    q_at = jnp.take_along_axis(q_pad, a[:, None, None], axis=1)[:, 0]
    res = jnp.clip(p_at - q_at, 0.0)
    tot = res.sum(-1, keepdims=True)
    # tot == 0 only when p == q at the rejected position — a
    # measure-zero event under exact arithmetic; fall back to p.
    safe = jnp.where(tot > 0, res / jnp.where(tot > 0, tot, 1.0), p_at)
    final = jax.vmap(lambda fk, pr: jax.random.categorical(
        fk, jnp.log(pr + 1e-38)))(final_keys, safe).astype(d_block.dtype)
    emit = _assemble_emit(d_block, a, final)
    return emit.astype(d_block.dtype), emitted, a, final


class DepthController:
    """Acceptance-adaptive draft-depth selector over a fixed bucket set.

    The serving engine precompiles one speculative program per depth in
    ``depths`` (``k`` is a static argument of its round program — the
    controller only ever SELECTS among compiled programs, it never
    changes any program's math).  Per harvested round the engine feeds
    back how many tokens the draft proposed and how many the target
    accepted; the controller keeps an EWMA of the acceptance rate and
    walks the bucket ladder: deepen one bucket when acceptance holds
    above ``deepen``, back off one when it collapses below ``backoff``,
    and never move again within ``dwell`` rounds of the last move (the
    hysteresis that bounds the switch rate — at most one switch per
    ``dwell`` rounds).  Depth 0 (plain decode through the k=0 round
    program, draft cache kept in lockstep) yields no acceptance signal,
    so a deterministic PROBE fires every ``probe_every``-th round at
    depth 0: one round at the shallowest nonzero depth, kept only if
    its acceptance clears ``deepen``.

    Decisions are a deterministic function of the observe() history
    ONLY — round wall times are recorded per depth for telemetry
    (gauges, trace timelines) but never consulted, so a forced-depth
    engine replays bitwise regardless of host timing.
    """

    def __init__(self, depths, *, start=None, alpha=0.4,
                 deepen=0.7, backoff=0.35, dwell=4, probe_every=16):
        ds = sorted(set(int(d) for d in depths))
        if not ds or ds[0] < 0:
            raise ValueError(f"depths must be non-negative, got {depths}")
        if len(ds) < 2:
            raise ValueError(
                f"need >= 2 depth buckets to adapt over, got {ds} "
                "(a single depth is just the fixed engine)")
        if ds[-1] < 1:
            raise ValueError("need at least one nonzero depth")
        if not 0.0 <= backoff < deepen <= 1.0:
            raise ValueError(
                f"need 0 <= backoff < deepen <= 1, got "
                f"backoff={backoff}, deepen={deepen}")
        self.depths = tuple(ds)
        self.alpha = float(alpha)
        self.deepen_at = float(deepen)
        self.backoff_at = float(backoff)
        self.dwell = max(1, int(dwell))
        self.probe_every = max(2, int(probe_every))
        if start is None:
            start = ds[-1]
        if start not in ds:
            raise ValueError(f"start depth {start} not in buckets {ds}")
        self._i = ds.index(start)
        self._ewma = None           # no signal yet
        self._since_switch = 0      # rounds at the current depth
        self._zero_rounds = 0       # consecutive rounds at depth 0
        self._probing = False       # current round is a depth-0 probe
        self.rounds = 0
        self.switches = 0
        # Telemetry only: per-depth round counts and wall-time EWMAs.
        self._stats = {d: {"rounds": 0, "wall_ewma": None,
                           "acc_ewma": None} for d in self.depths}

    def depth(self) -> int:
        """Depth for the NEXT dispatched round."""
        return self.depths[self._i]

    def acceptance(self):
        """Current acceptance-rate EWMA (None before any signal)."""
        return self._ewma

    def _move(self, i: int) -> None:
        if i != self._i:
            self._i = i
            self.switches += 1
            self._since_switch = 0
            self._ewma = None       # judge the new depth on its own

    def observe(self, drafted: int, accepted: int,
                wall_s=None) -> None:
        """Feed back one harvested round: ``drafted`` tokens proposed
        across active slots (active * k), ``accepted`` of them kept."""
        d = self.depths[self._i]
        self.rounds += 1
        self._since_switch += 1
        st = self._stats[d]
        st["rounds"] += 1
        if wall_s is not None:
            st["wall_ewma"] = (float(wall_s) if st["wall_ewma"] is None
                               else (1 - self.alpha) * st["wall_ewma"]
                               + self.alpha * float(wall_s))
        if d > 0 and drafted > 0:
            rate = accepted / drafted
            self._ewma = (rate if self._ewma is None
                          else (1 - self.alpha) * self._ewma
                          + self.alpha * rate)
            st["acc_ewma"] = self._ewma
        if self._probing:
            # One-round probe out of depth 0: keep the climb only if
            # the probe's own acceptance clears the deepen bar.
            self._probing = False
            self._zero_rounds = 0
            if self._ewma is None or self._ewma < self.deepen_at:
                self._move(0)
            return
        if d == 0:
            self._zero_rounds += 1
            if self._zero_rounds >= self.probe_every:
                self._probing = True
                self._move(self._shallowest_nonzero())
            return
        if self._since_switch < self.dwell or self._ewma is None:
            return
        if self._ewma >= self.deepen_at and self._i + 1 < len(
                self.depths):
            self._move(self._i + 1)
        elif self._ewma <= self.backoff_at and self._i > 0:
            self._move(self._i - 1)

    def _shallowest_nonzero(self) -> int:
        for i, d in enumerate(self.depths):
            if d > 0:
                return i
        raise AssertionError("ctor guarantees a nonzero depth")

    def telemetry(self) -> dict:
        """Controller snapshot (copies; exposure only): current depth,
        total rounds/switches, acceptance EWMA, and per-depth round
        counts / wall+acceptance EWMAs."""
        return {
            "depth": self.depth(),
            "rounds": self.rounds,
            "switches": self.switches,
            "acceptance": self._ewma,
            "per_depth": {d: dict(v) for d, v in self._stats.items()},
        }


def _set_cache_index(cache, value):
    """Roll every layer's cache index to ``value`` (scan-stacked index
    leaves broadcast the scalar)."""
    def fix(path, leaf):
        if path[-1].key == "index":
            return jnp.broadcast_to(value, leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def generate_speculative(target_config: LlamaConfig, target_params,
                         draft_config: LlamaConfig, draft_params,
                         prompt: jax.Array, max_new_tokens: int, *,
                         k: int = 4, cast_params: bool = True,
                         temperature: float = 0.0, top_k=None,
                         top_p=None, seed: int = 0):
    """Decode of ``max_new_tokens`` via draft speculation.

    Returns ``(tokens [1, S+max_new], accepted_rounds_stats)`` where the
    stats dict carries ``rounds`` and ``drafted_accepted`` (host ints,
    for measuring acceptance rate).  ``temperature`` 0 (default):
    greedy — output tokens are identical to
    ``generate(target_config, target_params, prompt, max_new_tokens)``.
    ``temperature`` > 0: the draft samples its proposals (same
    temperature/top_k/top_p filters as the target) and acceptance uses
    the rejection rule (``sampled_accept``), so outputs follow the SAME
    distribution as plain sampled decoding from the target; ``seed``
    names the rng stream (deterministic per seed).
    """
    from tensorflow_train_distributed_tpu.models.generate import (
        validate_sampling,
    )

    validate_sampling(temperature, top_k, top_p)
    if prompt.ndim != 2 or prompt.shape[0] != 1:
        raise ValueError(
            f"speculative decode is batch-1 (per-row acceptance lengths "
            f"need per-row cache indices); got shape {prompt.shape}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens}")
    if k < 1:
        raise ValueError(f"k (draft block length) must be >= 1, got {k}")
    if draft_config.vocab_size != target_config.vocab_size:
        raise ValueError(
            f"draft vocab {draft_config.vocab_size} != target vocab "
            f"{target_config.vocab_size}: token ids would not line up")
    _reject_config("target", target_config)
    _reject_config("draft", draft_config)
    total = prompt.shape[1] + max_new_tokens + k + 1
    if total > target_config.max_positions:
        raise ValueError(
            f"prompt + max_new + k+1 = {total} exceeds the target's "
            f"max_positions {target_config.max_positions}")
    if total > draft_config.max_positions:
        raise ValueError(
            f"prompt + max_new + k+1 = {total} exceeds the draft's "
            f"max_positions {draft_config.max_positions}")
    from tensorflow_train_distributed_tpu.models.generate import (
        cast_floating,
        has_lora_leaves,
    )

    for name, p in (("target", target_params), ("draft", draft_params)):
        if any(getattr(x, "dtype", None) == jnp.int8
               for x in jax.tree.leaves(p)):
            raise ValueError(
                f"{name} params are int8-quantized: speculative decode "
                "has no dequant path — pass full-precision trees "
                "(generate() handles int8 serving)")
        if has_lora_leaves(p):
            raise ValueError(
                f"{name} params carry unmerged LoRA adapters — fold them "
                "in first (models.lora.merge_lora)")
    if cast_params:
        target_params = cast_floating(target_params, target_config.dtype)
        draft_params = cast_floating(draft_params, draft_config.dtype)
    out, rounds, accepted = _speculate(
        target_config, draft_config, int(max_new_tokens), int(k),
        float(temperature), top_k, top_p,
        target_params, draft_params, prompt,
        jnp.uint32(seed))
    stats = {"rounds": int(rounds),
             "drafted_accepted": int(accepted),
             "tokens": int(max_new_tokens)}
    return out, stats


@compile_site(buckets="exact (offline batch API: one compile per "
                      "prompt shape / sampling config)",
              donates=(), statics=(),
              static_names=("target_config", "draft_config",
                            "max_new", "k", "temperature",
                            "top_k", "top_p"),
              max_compiles=None)
@partial(jax.jit, static_argnames=("target_config", "draft_config",
                                   "max_new", "k", "temperature",
                                   "top_k", "top_p"))
def _speculate(target_config, draft_config, max_new, k,
               temperature, top_k, top_p,
               target_params, draft_params, prompt, seed):
    from tensorflow_train_distributed_tpu.models.generate import (
        filter_logits,
    )

    greedy = temperature == 0.0
    stream = jax.random.key(seed)

    def _filter(lg):
        return filter_logits(lg, temperature=temperature, top_k=top_k,
                             top_p=top_p)

    prompt_len = prompt.shape[1]
    cache_len = prompt_len + max_new + k + 1
    target = LlamaModel(target_config, decode=True, cache_len=cache_len)
    draft = LlamaModel(draft_config, decode=True, cache_len=cache_len)

    # Prefill both on the prompt; the target's last logit emits token 1
    # (draw index 0 of the stream when sampling).
    t_logits, t_vars = target.apply({"params": target_params}, prompt,
                                    mutable=["cache"])
    _, d_vars = draft.apply({"params": draft_params}, prompt,
                            mutable=["cache"])
    last = t_logits[:, -1].astype(jnp.float32)       # [1, V]
    if greedy:
        tok0 = jnp.argmax(last, axis=-1).astype(prompt.dtype)  # [1]
    else:
        tok0 = jax.random.categorical(
            jax.random.fold_in(stream, 0), _filter(last)[0]
        )[None].astype(prompt.dtype)

    out0 = jnp.zeros((1, max_new + k + 1), prompt.dtype)
    out0 = out0.at[:, 0].set(tok0)

    def body(carry):
        d_cache, t_cache, tok, done, out, rounds, acc_total = carry
        ctx = prompt_len + done - 1  # non-prompt rows both caches hold
        # Per-round key: ``done`` strictly increases (every round emits
        # >= 1 token), so no round reuses a key; draw indices within
        # the round are 0..k (draft), k+1 (uniforms), k+2 (final) —
        # the same layout as the serving engine's per-slot streams.
        round_key = jax.random.fold_in(stream, done)

        # Draft k+1 steps: inputs [tok, d0..d_{k-1}] -> emits d0..dk.
        # The k+1-th step is append-only (dk discarded) so the draft
        # cache finishes holding the SAME row set as the target's, and
        # both roll back by one rule below.
        def scan_step(c, j):
            cache, t = c
            logits_d, upd = draft.apply(
                {"params": draft_params, "cache": cache}, t[:, None],
                mutable=["cache"])
            lg = logits_d[:, -1].astype(jnp.float32)    # [1, V]
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(t.dtype)
                return (upd["cache"], nxt), nxt
            filt = _filter(lg)
            nxt = jax.random.categorical(
                jax.random.fold_in(round_key, j), filt[0]
            )[None].astype(t.dtype)
            return (upd["cache"], nxt), (nxt, jax.nn.softmax(filt, -1))

        (d_cache, _), scanned = jax.lax.scan(
            scan_step, (d_cache, tok), jnp.arange(k + 1))
        drafts = (scanned if greedy else scanned[0])[:, 0]
        d_block = drafts[:k]             # d0..d_{k-1}; dk unused

        # Target verifies [tok, d0..d_{k-1}] in one k+1-token call.
        block = jnp.concatenate([tok, d_block], axis=0)[None, :]  # [1,k+1]
        logits, t_upd = target.apply(
            {"params": target_params, "cache": t_cache}, block,
            mutable=["cache"])
        t_cache = t_upd["cache"]

        if greedy:
            preds = jnp.argmax(logits[0].astype(jnp.float32),
                               axis=-1).astype(tok.dtype)  # [k+1]
            # a = leading i with d_i == n_i; emit d0..d_{a-1} then n_a
            # (shared batched rule; batch of 1 here).
            emit_b, emitted_b, a_b, next_b = accept_block(
                d_block[None, :], preds[None, :])
        else:
            q = jnp.moveaxis(scanned[1][:k], 0, 1)       # [1, k, V]
            p = jax.nn.softmax(
                _filter(logits.astype(jnp.float32)), axis=-1)
            us = jax.random.uniform(
                jax.random.fold_in(round_key, k + 1), (1, k))
            emit_b, emitted_b, a_b, next_b = sampled_accept(
                d_block[None, :].astype(jnp.int32), q, p, us,
                jax.random.fold_in(round_key, k + 2)[None])
            emit_b = emit_b.astype(tok.dtype)
            next_b = next_b.astype(tok.dtype)
        a, emitted = a_b[0], emitted_b[0]
        out = jax.lax.dynamic_update_slice(out, emit_b, (0, done))

        # Roll both caches back to the accepted context.
        new_index = ctx + emitted
        d_cache = _set_cache_index(d_cache, new_index)
        t_cache = _set_cache_index(t_cache, new_index)
        return (d_cache, t_cache, next_b, done + emitted, out,
                rounds + 1, acc_total + a)

    def cond(carry):
        return carry[3] < max_new

    init = (d_vars["cache"], t_vars["cache"], tok0, jnp.asarray(1),
            out0, jnp.asarray(0), jnp.asarray(0))
    _, _, _, done, out, rounds, acc_total = jax.lax.while_loop(
        cond, body, init)
    return (jnp.concatenate([prompt, out[:, :max_new]], axis=1),
            rounds, acc_total)
