"""Transformer-big encoder-decoder for WMT en-de — reference config[3].

The reference trains this with a Horovod allreduce hook around a custom
loop (SURVEY.md §3.2); here the allreduce is GSPMD's and the custom loop is
the standard Trainer.  Architecture follows the classic "big" setting:
6+6 layers, d_model 1024, 16 heads, FFN 4096, sinusoidal positions, pre-LN
(the variant that trains stably without the reference's warmup fragility),
label smoothing 0.1.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models import layers as L
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    compile_site,
)
from tensorflow_train_distributed_tpu.ops.losses import (
    fold_sample_weight, softmax_cross_entropy,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 1024
    num_heads: int = 16
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    ffn_size: int = 4096
    max_positions: int = 1024
    dropout_rate: float = 0.1
    label_smoothing: float = 0.1
    dtype: object = jnp.float32
    # Activation checkpointing per layer (jax.checkpoint via nn.remat):
    # trades recompute for activation memory — the big-batch enabler for
    # transformer_big on small-HBM chips.
    remat: bool = False


TRANSFORMER_PRESETS = {
    "transformer_big": TransformerConfig(),
    "transformer_base": TransformerConfig(d_model=512, num_heads=8,
                                          ffn_size=2048),
    "transformer_tiny": TransformerConfig(
        vocab_size=256, d_model=32, num_heads=2, num_encoder_layers=2,
        num_decoder_layers=2, ffn_size=64, max_positions=128,
        dropout_rate=0.0),
}


class EncoderLayer(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        x = x + L.MultiHeadAttention(
            num_heads=cfg.num_heads, head_dim=cfg.d_model // cfg.num_heads,
            dtype=cfg.dtype, dropout_rate=cfg.dropout_rate,
            name="self_attention",
        )(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        return x + L.MlpBlock(hidden=cfg.ffn_size, dtype=cfg.dtype,
                              dropout_rate=cfg.dropout_rate, name="mlp",
                              )(h, deterministic=deterministic)


class DecoderLayer(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, y, enc, deterministic: bool = True):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype)(y)
        y = y + L.MultiHeadAttention(
            num_heads=cfg.num_heads, head_dim=cfg.d_model // cfg.num_heads,
            dtype=cfg.dtype, causal=True, dropout_rate=cfg.dropout_rate,
            name="self_attention",
        )(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype)(y)
        y = y + L.MultiHeadAttention(
            num_heads=cfg.num_heads, head_dim=cfg.d_model // cfg.num_heads,
            dtype=cfg.dtype, dropout_rate=cfg.dropout_rate,
            name="cross_attention",
        )(h, enc, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype)(y)
        return y + L.MlpBlock(hidden=cfg.ffn_size, dtype=cfg.dtype,
                              dropout_rate=cfg.dropout_rate, name="mlp",
                              )(h, deterministic=deterministic)


class Seq2SeqTransformer(nn.Module):
    config: TransformerConfig = TransformerConfig()

    def setup(self):
        cfg = self.config
        self.embed = L.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                             name="shared_embed")
        self.pos_table = L.sinusoidal_positions(cfg.max_positions,
                                                cfg.d_model)
        # nn.remat is a transparent lift: param names/structure (and so
        # checkpoints) are identical with and without it.  deterministic
        # is a static argnum — a python bool must not be traced.
        enc_cls, dec_cls = EncoderLayer, DecoderLayer
        if cfg.remat:
            enc_cls = nn.remat(EncoderLayer, static_argnums=(2,))
            dec_cls = nn.remat(DecoderLayer, static_argnums=(3,))
        self.enc_layers = [enc_cls(cfg, name=f"enc_{i}")
                           for i in range(cfg.num_encoder_layers)]
        self.dec_layers = [dec_cls(cfg, name=f"dec_{i}")
                           for i in range(cfg.num_decoder_layers)]
        self.enc_norm = nn.LayerNorm(dtype=cfg.dtype, name="enc_norm")
        self.dec_norm = nn.LayerNorm(dtype=cfg.dtype, name="dec_norm")

    def _pos(self, x):
        scale = jnp.asarray(self.config.d_model, jnp.float32) ** 0.5
        return x * scale.astype(x.dtype) + jnp.asarray(
            self.pos_table[: x.shape[1]], x.dtype)[None]

    def encode(self, inputs, *, deterministic: bool = True):
        x = self._pos(self.embed(inputs))
        for layer in self.enc_layers:
            x = layer(x, deterministic)  # positional: remat static argnum
        return self.enc_norm(x)

    def decode(self, targets_in, enc, *, deterministic: bool = True):
        y = self._pos(self.embed(targets_in))
        for layer in self.dec_layers:
            y = layer(y, enc, deterministic)
        y = self.dec_norm(y)
        logits = self.embed.attend(y)  # tied softmax (big-model convention)
        return nn.with_logical_constraint(
            logits, ("batch", "length", "vocab"))

    def __call__(self, inputs, targets_in, *, deterministic: bool = True):
        enc = self.encode(inputs, deterministic=deterministic)
        return self.decode(targets_in, enc, deterministic=deterministic)


class Seq2SeqTask:
    """WMT-style objective over ``SyntheticWMT`` batches."""

    def __init__(self, config: TransformerConfig = TransformerConfig()):
        self.config = config
        self.model = Seq2SeqTransformer(config)

    def init_variables(self, rng, batch):
        return self.model.init(rng, batch["inputs"], batch["targets_in"])

    def loss_fn(self, params, model_state, batch, rng, train):
        logits = self.model.apply(
            {"params": params}, batch["inputs"], batch["targets_in"],
            deterministic=not train,
            rngs={"dropout": rng} if train else {},
        ).astype(jnp.float32)
        weights = fold_sample_weight(batch, batch["targets_out"].shape)
        loss, acc = softmax_cross_entropy(
            logits, batch["targets_out"],
            label_smoothing=self.config.label_smoothing, weights=weights)
        metrics = {"accuracy": acc}
        if weights is not None:
            # Task contract: report total weight (unclamped) so padded
            # batches combine as the true weighted mean across steps.
            metrics["loss_weight"] = weights.sum()
        return loss, (metrics, model_state)


def make_task(config: TransformerConfig = TRANSFORMER_PRESETS[
        "transformer_big"]) -> Seq2SeqTask:
    return Seq2SeqTask(config)


@compile_site(buckets="exact (WMT eval batches: one compile per "
                      "source-batch shape / max_len)",
              donates=(), statics=(),
              static_names=("config", "max_len", "bos_id", "eos_id",
                            "pad_id"),
              max_compiles=None)
@partial(jax.jit, static_argnames=("config", "max_len", "bos_id", "eos_id",
                                   "pad_id"))
def greedy_translate(config: TransformerConfig, params, inputs,
                     *, max_len: int, bos_id: int, eos_id: int,
                     pad_id: int = 0):
    """Greedy seq2seq decoding: [B, S] source ids → [B, max_len] targets.

    One jit, static output length, ``lax.fori_loop`` over positions: the
    encoder runs once, the decoder re-runs over the (static-shape) target
    buffer each step — causal self-attention makes position ``i``'s logits
    depend only on the filled prefix, so the padded tail is inert.  O(n²)
    decoder work without KV-cache machinery: the right trade for WMT eval
    batches (the reference's config[3] never decodes in its training loop
    at all; this closes the eval loop natively).

    Output row = first token onward (BOS excluded); positions after EOS
    are ``pad_id``.
    """
    model = Seq2SeqTransformer(config)
    enc = model.apply({"params": params}, inputs, method="encode")
    b = inputs.shape[0]
    ys = jnp.full((b, max_len + 1), pad_id, jnp.int32)
    ys = ys.at[:, 0].set(bos_id)
    finished0 = jnp.zeros((b,), bool)

    def body(i, carry):
        ys, finished = carry
        logits = model.apply({"params": params}, ys[:, :-1], enc,
                             method="decode")
        nxt = jnp.argmax(logits[:, i].astype(jnp.float32), axis=-1)
        nxt = jnp.where(finished, pad_id, nxt).astype(jnp.int32)
        ys = ys.at[:, i + 1].set(nxt)
        return ys, finished | (nxt == eos_id)

    ys, _ = jax.lax.fori_loop(0, max_len, body, (ys, finished0))
    return ys[:, 1:]


@compile_site(buckets="exact (WMT eval batches: one compile per "
                      "source-batch shape / max_len / beam)",
              donates=(), statics=(),
              static_names=("config", "max_len", "beam_size",
                            "bos_id", "eos_id", "pad_id"),
              max_compiles=None)
@partial(jax.jit, static_argnames=("config", "max_len", "beam_size",
                                   "bos_id", "eos_id", "pad_id"))
def beam_translate(config: TransformerConfig, params, inputs,
                   *, max_len: int, beam_size: int = 4,
                   bos_id: int, eos_id: int, pad_id: int = 0,
                   length_alpha: float = 0.6):
    """Beam-search seq2seq decoding: [B, S] sources → [B, max_len] targets.

    The WMT convention the reference's Transformer-big config evaluates
    under (beam 4, GNMT length penalty ((5+l)/6)^alpha, alpha 0.6); greedy
    is the beam_size=1 special case.  TPU-first mechanics match
    ``greedy_translate``: one jit, static shapes, ``lax.fori_loop`` over
    positions, encoder run once — beams ride the batch dimension
    ([B, K] flattened to B·K) so the decoder sees one big static batch.

    Single-buffer variant: a finished beam (emitted EOS) can only extend
    with ``pad_id`` at zero added cost, freezing its raw score; the final
    winner per row is argmax of cumulative log-prob / GNMT length penalty.
    (The dual live/finished buffer of GNMT/T5X differs only when a short
    finished hypothesis should *lose* its slot to a longer live one
    mid-search — beams here are never reclaimed once finished.)

    Returns [B, max_len] int32; positions after EOS are ``pad_id``.
    """
    model = Seq2SeqTransformer(config)
    b = inputs.shape[0]
    k = beam_size
    enc = model.apply({"params": params}, inputs, method="encode")
    # [B, S, D] → [B·K, S, D], beams contiguous per row.
    enc = jnp.repeat(enc, k, axis=0)

    ys = jnp.full((b, k, max_len + 1), pad_id, jnp.int32)
    ys = ys.at[:, :, 0].set(bos_id)
    # Beam 0 starts at 0; the rest at -inf so step 0 doesn't pick K copies
    # of the same token from identical prefixes.
    neg_inf = jnp.asarray(-1e9, jnp.float32)
    scores = jnp.tile(jnp.array([0.0] + [float(-1e9)] * (k - 1),
                                jnp.float32), (b, 1))
    finished = jnp.zeros((b, k), bool)
    lengths = jnp.zeros((b, k), jnp.int32)  # tokens generated (incl. EOS)

    vocab = config.vocab_size

    def body(i, carry):
        ys, scores, finished, lengths = carry
        logits = model.apply(
            {"params": params}, ys.reshape(b * k, -1)[:, :-1], enc,
            method="decode")
        logp = jax.nn.log_softmax(
            logits[:, i].astype(jnp.float32)).reshape(b, k, vocab)
        # Finished beams: only pad continues, at zero added cost.
        pad_only = jnp.full((vocab,), -1e9, jnp.float32).at[pad_id].set(0.0)
        logp = jnp.where(finished[:, :, None], pad_only[None, None], logp)
        cand = scores[:, :, None] + logp                  # [B, K, V]
        top, idx = jax.lax.top_k(cand.reshape(b, k * vocab), k)
        beam_idx, tok = idx // vocab, (idx % vocab).astype(jnp.int32)
        take = lambda t: jnp.take_along_axis(  # noqa: E731
            t, beam_idx.reshape(beam_idx.shape + (1,) * (t.ndim - 2)),
            axis=1)
        ys = take(ys).at[:, :, i + 1].set(tok)
        was_done = jnp.take_along_axis(finished, beam_idx, axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
        lengths = jnp.where(was_done, lengths, lengths + 1)
        return ys, top, was_done | (tok == eos_id), lengths

    ys, scores, finished, lengths = jax.lax.fori_loop(
        0, max_len, body, (ys, scores, finished, lengths))
    # GNMT length penalty on the final cumulative scores.
    lp = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** length_alpha
    best = jnp.argmax(jnp.where(scores <= neg_inf / 2, neg_inf,
                                scores / lp), axis=1)
    out = jnp.take_along_axis(ys, best[:, None, None], axis=1)[:, 0]
    return out[:, 1:]
