"""Transformer-big encoder-decoder for WMT en-de — reference config[3].

The reference trains this with a Horovod allreduce hook around a custom
loop (SURVEY.md §3.2); here the allreduce is GSPMD's and the custom loop is
the standard Trainer.  Architecture follows the classic "big" setting:
6+6 layers, d_model 1024, 16 heads, FFN 4096, sinusoidal positions, pre-LN
(the variant that trains stably without the reference's warmup fragility),
label smoothing 0.1.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.models import layers as L
from tensorflow_train_distributed_tpu.ops.losses import softmax_cross_entropy


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 1024
    num_heads: int = 16
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    ffn_size: int = 4096
    max_positions: int = 1024
    dropout_rate: float = 0.1
    label_smoothing: float = 0.1
    dtype: object = jnp.float32


TRANSFORMER_PRESETS = {
    "transformer_big": TransformerConfig(),
    "transformer_base": TransformerConfig(d_model=512, num_heads=8,
                                          ffn_size=2048),
    "transformer_tiny": TransformerConfig(
        vocab_size=256, d_model=32, num_heads=2, num_encoder_layers=2,
        num_decoder_layers=2, ffn_size=64, max_positions=128,
        dropout_rate=0.0),
}


class EncoderLayer(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        x = x + L.MultiHeadAttention(
            num_heads=cfg.num_heads, head_dim=cfg.d_model // cfg.num_heads,
            dtype=cfg.dtype, dropout_rate=cfg.dropout_rate,
            name="self_attention",
        )(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        return x + L.MlpBlock(hidden=cfg.ffn_size, dtype=cfg.dtype,
                              dropout_rate=cfg.dropout_rate, name="mlp",
                              )(h, deterministic=deterministic)


class DecoderLayer(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, y, enc, *, deterministic: bool = True):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype)(y)
        y = y + L.MultiHeadAttention(
            num_heads=cfg.num_heads, head_dim=cfg.d_model // cfg.num_heads,
            dtype=cfg.dtype, causal=True, dropout_rate=cfg.dropout_rate,
            name="self_attention",
        )(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype)(y)
        y = y + L.MultiHeadAttention(
            num_heads=cfg.num_heads, head_dim=cfg.d_model // cfg.num_heads,
            dtype=cfg.dtype, dropout_rate=cfg.dropout_rate,
            name="cross_attention",
        )(h, enc, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype)(y)
        return y + L.MlpBlock(hidden=cfg.ffn_size, dtype=cfg.dtype,
                              dropout_rate=cfg.dropout_rate, name="mlp",
                              )(h, deterministic=deterministic)


class Seq2SeqTransformer(nn.Module):
    config: TransformerConfig = TransformerConfig()

    def setup(self):
        cfg = self.config
        self.embed = L.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                             name="shared_embed")
        self.pos_table = L.sinusoidal_positions(cfg.max_positions,
                                                cfg.d_model)
        self.enc_layers = [EncoderLayer(cfg, name=f"enc_{i}")
                           for i in range(cfg.num_encoder_layers)]
        self.dec_layers = [DecoderLayer(cfg, name=f"dec_{i}")
                           for i in range(cfg.num_decoder_layers)]
        self.enc_norm = nn.LayerNorm(dtype=cfg.dtype, name="enc_norm")
        self.dec_norm = nn.LayerNorm(dtype=cfg.dtype, name="dec_norm")

    def _pos(self, x):
        scale = jnp.asarray(self.config.d_model, jnp.float32) ** 0.5
        return x * scale.astype(x.dtype) + jnp.asarray(
            self.pos_table[: x.shape[1]], x.dtype)[None]

    def encode(self, inputs, *, deterministic: bool = True):
        x = self._pos(self.embed(inputs))
        for layer in self.enc_layers:
            x = layer(x, deterministic=deterministic)
        return self.enc_norm(x)

    def decode(self, targets_in, enc, *, deterministic: bool = True):
        y = self._pos(self.embed(targets_in))
        for layer in self.dec_layers:
            y = layer(y, enc, deterministic=deterministic)
        y = self.dec_norm(y)
        logits = self.embed.attend(y)  # tied softmax (big-model convention)
        return nn.with_logical_constraint(
            logits, ("batch", "length", "vocab"))

    def __call__(self, inputs, targets_in, *, deterministic: bool = True):
        enc = self.encode(inputs, deterministic=deterministic)
        return self.decode(targets_in, enc, deterministic=deterministic)


class Seq2SeqTask:
    """WMT-style objective over ``SyntheticWMT`` batches."""

    def __init__(self, config: TransformerConfig = TransformerConfig()):
        self.config = config
        self.model = Seq2SeqTransformer(config)

    def init_variables(self, rng, batch):
        return self.model.init(rng, batch["inputs"], batch["targets_in"])

    def loss_fn(self, params, model_state, batch, rng, train):
        logits = self.model.apply(
            {"params": params}, batch["inputs"], batch["targets_in"],
            deterministic=not train,
            rngs={"dropout": rng} if train else {},
        ).astype(jnp.float32)
        loss, acc = softmax_cross_entropy(
            logits, batch["targets_out"],
            label_smoothing=self.config.label_smoothing)
        return loss, ({"accuracy": acc}, model_state)


def make_task(config: TransformerConfig = TRANSFORMER_PRESETS[
        "transformer_big"]) -> Seq2SeqTask:
    return Seq2SeqTask(config)


@partial(jax.jit, static_argnames=("config", "max_len", "bos_id", "eos_id",
                                   "pad_id"))
def greedy_translate(config: TransformerConfig, params, inputs,
                     *, max_len: int, bos_id: int, eos_id: int,
                     pad_id: int = 0):
    """Greedy seq2seq decoding: [B, S] source ids → [B, max_len] targets.

    One jit, static output length, ``lax.fori_loop`` over positions: the
    encoder runs once, the decoder re-runs over the (static-shape) target
    buffer each step — causal self-attention makes position ``i``'s logits
    depend only on the filled prefix, so the padded tail is inert.  O(n²)
    decoder work without KV-cache machinery: the right trade for WMT eval
    batches (the reference's config[3] never decodes in its training loop
    at all; this closes the eval loop natively).

    Output row = first token onward (BOS excluded); positions after EOS
    are ``pad_id``.
    """
    model = Seq2SeqTransformer(config)
    enc = model.apply({"params": params}, inputs, method="encode")
    b = inputs.shape[0]
    ys = jnp.full((b, max_len + 1), pad_id, jnp.int32)
    ys = ys.at[:, 0].set(bos_id)
    finished0 = jnp.zeros((b,), bool)

    def body(i, carry):
        ys, finished = carry
        logits = model.apply({"params": params}, ys[:, :-1], enc,
                             method="decode")
        nxt = jnp.argmax(logits[:, i].astype(jnp.float32), axis=-1)
        nxt = jnp.where(finished, pad_id, nxt).astype(jnp.int32)
        ys = ys.at[:, i + 1].set(nxt)
        return ys, finished | (nxt == eos_id)

    ys, _ = jax.lax.fori_loop(0, max_len, body, (ys, finished0))
    return ys[:, 1:]
