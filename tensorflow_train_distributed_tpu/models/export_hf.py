"""Export native Llama-family checkpoints AS HuggingFace models.

The inverse of ``models.import_hf`` — closes the interop loop for the
reference's SFT story (SURVEY.md §2.1 config[4]): fine-tune here on TPU
meshes, then hand the result to any HF-stack consumer
(``AutoModelForCausalLM.from_pretrained`` loads the exported directory
directly; forward parity and import→export→import round trips are
tested).  Windowed configs export as ``model_type: mistral`` so the HF
side applies the same sliding-window masking.

Weight conventions mirror import_hf exactly in reverse: flax ``[in,
out]`` kernels transpose back to torch ``[out, in]``; scan-stacked
layer params unstack into ``model.layers.{i}.*``; the head is always
written explicitly (``tie_word_embeddings: false``).  Params may be
live (possibly sharded) jax arrays — leaves are gathered with
``np.asarray``, so every shard must be addressable from this host.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from tensorflow_train_distributed_tpu.models.llama import LlamaConfig


def hf_config_dict(config: LlamaConfig) -> dict:
    """``config.json`` contents for the exported checkpoint."""
    if config.attention_sinks:
        raise ValueError(
            "attention_sinks have no HF config field — export the model "
            "without sinks (they are a decode-time technique; the "
            "weights are identical)")
    if (getattr(config, "embed_scale", False)
            or getattr(config, "norm_zero_centered", False)
            or getattr(config, "head_dim", None)
            or getattr(config, "mlp_activation", "silu") != "silu"):
        raise ValueError(
            "Gemma-convention configs (embed_scale / zero-centered "
            "norms / decoupled head_dim / GeGLU) have no HF exporter "
            "yet — the llama/mistral/qwen2 formats would silently "
            "change semantics; keep native (orbax) checkpoints")
    mistral = config.sliding_window is not None
    qwen2 = getattr(config, "qkv_bias", False)
    if qwen2 and mistral:
        raise ValueError(
            "qkv_bias + sliding_window exports are not supported (HF "
            "Qwen2 windows need max_window_layers plumbing) — export "
            "without the window (a decode-time technique)")
    head_dim = config.d_model // config.num_heads
    model_type = ("qwen2" if qwen2
                  else "mistral" if mistral else "llama")
    arch = {"qwen2": "Qwen2ForCausalLM",
            "mistral": "MistralForCausalLM",
            "llama": "LlamaForCausalLM"}[model_type]
    out = {
        "model_type": model_type,
        "architectures": [arch],
        "vocab_size": config.vocab_size,
        "hidden_size": config.d_model,
        "intermediate_size": config.ffn_size,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "num_key_value_heads": config.num_kv_heads or config.num_heads,
        "head_dim": head_dim,
        "max_position_embeddings": config.max_positions,
        "rms_norm_eps": config.rms_epsilon,
        "rope_theta": config.rope_base,
        "hidden_act": "silu",
        "tie_word_embeddings": False,
        "torch_dtype": "float32",
    }
    if mistral:
        out["sliding_window"] = config.sliding_window
    if getattr(config, "rope_scaling", None):
        f, lo, hi, old = config.rope_scaling
        out["rope_scaling"] = {
            "rope_type": "llama3", "factor": f, "low_freq_factor": lo,
            "high_freq_factor": hi,
            "original_max_position_embeddings": old}
    return out


def _t(x) -> "object":
    import torch

    return torch.from_numpy(np.asarray(x, np.float32))


def export_llama_state_dict(params, config: LlamaConfig) -> dict:
    """Native flax ``params`` tree → HF ``LlamaForCausalLM`` state dict
    (torch tensors, f32)."""
    import flax.linen as nn

    params = nn.unbox(params)  # strip LogicallyPartitioned metadata
    if config.scan_layers:
        import jax

        # Gather the stacked leaves host-side ONCE; per-layer slicing of
        # a ~13 GB 7B stack inside the loop would re-transfer the whole
        # model num_layers times.
        gathered = jax.tree_util.tree_map(
            np.asarray, params["layers"]["stack"]["block"])

        def layer(i):
            return jax.tree_util.tree_map(lambda x: x[i], gathered)
    else:
        def layer(i):
            return params[f"layer_{i}"]

    sd = {
        "model.embed_tokens.weight": _t(params["token_embed"]["embedding"]),
        "model.norm.weight": _t(params["final_norm"]["scale"]),
        "lm_head.weight": _t(np.asarray(
            params["lm_head"]["kernel"]).T),
    }
    for i in range(config.num_layers):
        lt = layer(i)
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = _t(lt["attn_norm"]["scale"])
        sd[p + "post_attention_layernorm.weight"] = _t(
            lt["mlp_norm"]["scale"])
        attn = lt["attention"]
        for hf, ours in (("q_proj", "query"), ("k_proj", "key"),
                         ("v_proj", "value"), ("o_proj", "out")):
            sd[p + f"self_attn.{hf}.weight"] = _t(
                np.asarray(attn[ours]["kernel"]).T)
            if getattr(config, "qkv_bias", False) and ours != "out":
                sd[p + f"self_attn.{hf}.bias"] = _t(attn[ours]["bias"])
        mlp = lt["mlp"]
        for hf, ours in (("gate_proj", "wi_gate"), ("up_proj", "wi_up"),
                         ("down_proj", "wo")):
            sd[p + f"mlp.{hf}.weight"] = _t(
                np.asarray(mlp[ours]["kernel"]).T)
    return sd


def export_llama(config: LlamaConfig, params, out_dir) -> Path:
    """Write an HF-loadable checkpoint directory (config.json +
    pytorch_model.bin); returns the directory path."""
    import torch

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "config.json").write_text(
        json.dumps(hf_config_dict(config), indent=2))
    torch.save(export_llama_state_dict(params, config),
               out / "pytorch_model.bin")
    return out


def hf_config_dict_mixtral(config) -> dict:
    """``config.json`` for a Mixtral (sparse-MoE) export."""
    head_dim = config.d_model // config.num_heads
    return {
        "model_type": "mixtral",
        "architectures": ["MixtralForCausalLM"],
        "vocab_size": config.vocab_size,
        "hidden_size": config.d_model,
        "intermediate_size": config.ffn_size,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "num_key_value_heads": config.num_kv_heads or config.num_heads,
        "head_dim": head_dim,
        "num_local_experts": config.num_experts,
        "num_experts_per_tok": config.top_k,
        "max_position_embeddings": config.max_positions,
        "rms_norm_eps": config.rms_epsilon,
        "rope_theta": config.rope_base,
        "hidden_act": "silu",
        "tie_word_embeddings": False,
        "torch_dtype": "float32",
        "sliding_window": None,
    }


def export_mixtral_state_dict(params, config) -> dict:
    """Native ``MoeLmModel`` params → HF ``MixtralForCausalLM`` state
    dict (the inverse of ``import_hf.import_mixtral_state_dict``):
    expert stacks unstack to ``experts.{e}.w1/w3/w2``, the f32 router
    kernel transposes back to ``block_sparse_moe.gate.weight``."""
    import flax.linen as nn

    if config.moe_every != 1:
        raise ValueError(
            "HF Mixtral has MoE on EVERY layer; this config's "
            f"moe_every={config.moe_every} is not representable")
    if getattr(config, "shared_expert_size", None):
        raise ValueError(
            "HF Mixtral has no shared expert; exporting would silently "
            f"drop the shared_mlp weights (shared_expert_size="
            f"{config.shared_expert_size}) — not representable")
    if getattr(config, "qkv_bias", False):
        raise ValueError(
            "HF Mixtral attention is bias-free; exporting would "
            "silently drop the q/k/v bias params — use the Qwen2-MoE "
            "format (export_qwen2_moe) for the full Qwen convention")
    if not getattr(config, "norm_topk_prob", True):
        raise ValueError(
            "HF Mixtral renormalizes top-k gates; this config's "
            "norm_topk_prob=False (raw softmax gates) is not "
            "representable — export_qwen2_moe carries the flag")
    params = nn.unbox(params)
    sd = {
        "model.embed_tokens.weight": _t(params["token_embed"]["embedding"]),
        "model.norm.weight": _t(params["final_norm"]["scale"]),
        "lm_head.weight": _t(np.asarray(params["lm_head"]["kernel"]).T),
    }
    for i in range(config.num_layers):
        lt = params[f"layer_{i}"]
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = _t(lt["attn_norm"]["scale"])
        sd[p + "post_attention_layernorm.weight"] = _t(
            lt["mlp_norm"]["scale"])
        attn = lt["attention"]
        for hf, ours in (("q_proj", "query"), ("k_proj", "key"),
                         ("v_proj", "value"), ("o_proj", "out")):
            sd[p + f"self_attn.{hf}.weight"] = _t(
                np.asarray(attn[ours]["kernel"]).T)
        moe_p = lt["moe"]
        sd[p + "block_sparse_moe.gate.weight"] = _t(
            np.asarray(moe_p["router"]["kernel"]).T)
        experts = moe_p["experts"]
        for e in range(config.num_experts):
            ep = p + f"block_sparse_moe.experts.{e}."
            for hf, ours in (("w1", "wi_gate"), ("w3", "wi_up"),
                             ("w2", "wo")):
                sd[ep + f"{hf}.weight"] = _t(
                    np.asarray(experts[ours]["kernel"][e]).T)
    return sd


def export_mixtral(config, params, out_dir) -> Path:
    """Write an HF-loadable Mixtral checkpoint directory."""
    import torch

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "config.json").write_text(
        json.dumps(hf_config_dict_mixtral(config), indent=2))
    torch.save(export_mixtral_state_dict(params, config),
               out / "pytorch_model.bin")
    return out


def export_hf_from_registry(config_name: str, checkpoint_dir,
                            out_dir, *, platform: str = "cpu",
                            lora_alpha: float = 16.0) -> Path:
    """CLI-oriented wrapper: registry llama-family config + orbax
    checkpoint → HF directory.  ``checkpoint_dir=None`` exports a fresh
    init (interop smoke test).  Checkpoints carrying LoRA adapters are
    merged first; ``lora_alpha`` must match the training value (the CLI
    default is 16.0) when the config itself does not carry the spec."""
    from tensorflow_train_distributed_tpu.models import registry
    from tensorflow_train_distributed_tpu.models.llama import CausalLmTask
    from tensorflow_train_distributed_tpu.runtime.mesh import force_platform

    if platform:
        force_platform(platform)
    from tensorflow_train_distributed_tpu.models.moe import MoeLmTask

    task = registry.get_entry(config_name)["task_factory"]()
    is_moe = isinstance(task, MoeLmTask)
    if not isinstance(task, (CausalLmTask, MoeLmTask)):
        raise SystemExit(
            f"--config {config_name} is not a Llama- or MoE-family "
            "decoder (HF export maps Llama/Mistral/Mixtral ForCausalLM "
            "checkpoints only)")
    config = task.config
    if is_moe:
        pass  # MoE export validated in export_mixtral_state_dict
    elif config.attention_sinks:
        # Sinks are decode-time; the exported weights are identical.
        import dataclasses

        config = dataclasses.replace(config, attention_sinks=0)
    if checkpoint_dir is not None:
        from tensorflow_train_distributed_tpu.training.checkpoint import (
            CheckpointManager,
        )

        mgr = CheckpointManager(str(checkpoint_dir), async_save=False)
        # Weights only (the purpose-built analysis-tool restore): a
        # decoder has no mutable model_state, and the optimizer moments
        # are irrelevant to the exported checkpoint.
        params = mgr.restore_params()
        mgr.close()
        if params is None:
            raise FileNotFoundError(f"no checkpoint under {checkpoint_dir}")
    else:
        import jax
        import numpy as np_

        toks = np_.zeros((1, 8), np_.int32)
        if is_moe:
            from tensorflow_train_distributed_tpu.models.moe import (
                MoeLmModel,
            )

            params = MoeLmModel(config).init(jax.random.key(0),
                                             toks)["params"]
        else:
            from tensorflow_train_distributed_tpu.models.llama import (
                LlamaModel,
            )

            params = LlamaModel(config).init(jax.random.key(0),
                                             toks)["params"]
    from tensorflow_train_distributed_tpu.models.generate import (
        has_lora_leaves,
    )

    if has_lora_leaves(params):
        # A LoRA fine-tune exports as a PLAIN HF model: fold the
        # adapters into the kernels first (HF loaders know nothing of
        # the lora_a/lora_b leaves and would silently drop them).
        # Rank comes from the adapter shapes; alpha must come from the
        # config or the caller (it is not recoverable from weights).
        import jax as _jax

        from tensorflow_train_distributed_tpu.models.lora import (
            LoraSpec, check_spec_matches, load_spec, merge_lora,
        )

        sidecar = (load_spec(checkpoint_dir)
                   if checkpoint_dir is not None else None)
        if sidecar is not None:
            spec = sidecar          # authoritative: written at train time
        elif config.lora is not None:
            spec = config.lora
        else:
            # Pre-sidecar checkpoint: rank AND targets are recoverable
            # from the adapter leaves; only alpha must come from the CLI.
            flat = _jax.tree_util.tree_flatten_with_path(params)[0]
            rank = next(v.shape[-1] for p, v in flat
                        if getattr(p[-1], "key", None) == "lora_a")
            targets = tuple(sorted({
                p[-2].key for p, _ in flat
                if getattr(p[-1], "key", None) == "lora_a"}))
            spec = LoraSpec(rank=rank, alpha=lora_alpha, targets=targets)
        check_spec_matches(params, spec)
        params = merge_lora(params, spec)
    if is_moe:
        if getattr(config, "shared_expert_size", None):
            # Gated shared expert + qkv biases = the Qwen2-MoE format
            # (Mixtral cannot represent the shared weights).
            return export_qwen2_moe(config, params, out_dir)
        return export_mixtral(config, params, out_dir)
    return export_llama(config, params, out_dir)


def hf_config_dict_qwen2_moe(config) -> dict:
    """``config.json`` for a Qwen2-MoE (gated-shared-expert) export."""
    return {
        "model_type": "qwen2_moe",
        "architectures": ["Qwen2MoeForCausalLM"],
        "vocab_size": config.vocab_size,
        "hidden_size": config.d_model,
        # HF's dense-layer width; unused at decoder_sparse_step=1 but
        # required by the config class — mirror the routed width.
        "intermediate_size": config.ffn_size,
        "moe_intermediate_size": config.ffn_size,
        "shared_expert_intermediate_size": config.shared_expert_size,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "num_key_value_heads": config.num_kv_heads or config.num_heads,
        "num_experts": config.num_experts,
        "num_experts_per_tok": config.top_k,
        "norm_topk_prob": bool(config.norm_topk_prob),
        "decoder_sparse_step": 1,
        "mlp_only_layers": [],
        "max_position_embeddings": config.max_positions,
        "rms_norm_eps": config.rms_epsilon,
        "rope_theta": config.rope_base,
        "hidden_act": "silu",
        "tie_word_embeddings": False,
        "torch_dtype": "float32",
        "use_sliding_window": False,
    }


def export_qwen2_moe_state_dict(params, config) -> dict:
    """Native shared-expert ``MoeLmModel`` params → HF
    ``Qwen2MoeForCausalLM`` state dict (inverse of
    ``import_hf.import_qwen2_moe_state_dict``)."""
    import flax.linen as nn

    if config.moe_every != 1:
        raise ValueError(
            "HF Qwen2-MoE (as exported here) has MoE on every layer; "
            f"moe_every={config.moe_every} is not representable")
    if (not getattr(config, "shared_expert_size", None)
            or not getattr(config, "shared_expert_gate", False)
            or not getattr(config, "qkv_bias", False)):
        raise ValueError(
            "Qwen2-MoE format needs the full Qwen convention: "
            "shared_expert_size set, shared_expert_gate=True and "
            "qkv_bias=True (plain Mixtral-style configs export via "
            "export_mixtral)")
    params = nn.unbox(params)
    sd = {
        "model.embed_tokens.weight": _t(params["token_embed"]["embedding"]),
        "model.norm.weight": _t(params["final_norm"]["scale"]),
        "lm_head.weight": _t(np.asarray(params["lm_head"]["kernel"]).T),
    }
    for i in range(config.num_layers):
        lt = params[f"layer_{i}"]
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = _t(lt["attn_norm"]["scale"])
        sd[p + "post_attention_layernorm.weight"] = _t(
            lt["mlp_norm"]["scale"])
        attn = lt["attention"]
        for hf, ours in (("q_proj", "query"), ("k_proj", "key"),
                         ("v_proj", "value")):
            sd[p + f"self_attn.{hf}.weight"] = _t(
                np.asarray(attn[ours]["kernel"]).T)
            sd[p + f"self_attn.{hf}.bias"] = _t(attn[ours]["bias"])
        sd[p + "self_attn.o_proj.weight"] = _t(
            np.asarray(attn["out"]["kernel"]).T)
        moe_p = lt["moe"]
        sd[p + "mlp.gate.weight"] = _t(
            np.asarray(moe_p["router"]["kernel"]).T)
        experts = moe_p["experts"]
        for e in range(config.num_experts):
            ep = p + f"mlp.experts.{e}."
            for hf, ours in (("gate_proj", "wi_gate"),
                             ("up_proj", "wi_up"), ("down_proj", "wo")):
                sd[ep + f"{hf}.weight"] = _t(
                    np.asarray(experts[ours]["kernel"][e]).T)
        shared = moe_p["shared_mlp"]
        for hf, ours in (("gate_proj", "wi_gate"), ("up_proj", "wi_up"),
                         ("down_proj", "wo")):
            sd[p + f"mlp.shared_expert.{hf}.weight"] = _t(
                np.asarray(shared[ours]["kernel"]).T)
        sd[p + "mlp.shared_expert_gate.weight"] = _t(
            np.asarray(moe_p["shared_gate"]["kernel"]).T)
    return sd


def export_qwen2_moe(config, params, out_dir) -> Path:
    """Write an HF-loadable Qwen2-MoE checkpoint directory."""
    import torch

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "config.json").write_text(
        json.dumps(hf_config_dict_qwen2_moe(config), indent=2))
    torch.save(export_qwen2_moe_state_dict(params, config),
               out / "pytorch_model.bin")
    return out
