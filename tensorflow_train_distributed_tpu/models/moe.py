"""Mixture-of-Experts decoder with expert parallelism.

NEW capability relative to the reference — it has no MoE anywhere
(SURVEY.md §2.4: EP absent; its nearest artifact is TPU embedding-table
sharding, ``tpu_embedding_v3.py:498``).  Included so the framework covers
the full dp/fsdp/tp/sp/ep/pp axis set.

TPU-native design — the GShard/Switch dense-dispatch formulation rather
than scatter/gather: tokens are routed per group g (one group per
sequence, riding the batch sharding), and moved with two einsums,

    expert_in[e,g,c,d] = Σ_s dispatch[g,s,e,c] · x[g,s,d]
    y[g,s,d]           = Σ_{e,c} combine[g,s,e,c] · out[e,g,c,d]

with per-group capacity c ≈ S·top_k·cf/E — cost linear in total tokens —
so the whole layer is static-shaped MXU work.  Expert weights carry the
``expert`` logical axis; under an ``expert``-sharded mesh GSPMD turns
those einsums into the all-to-all dispatch/return pattern automatically —
no hand-written collectives, and the same model runs unsharded on one
chip.  Capacity (``capacity_factor``) bounds per-expert token count, the
standard trick that keeps shapes static under jit (over-capacity tokens
fall through the residual connection).

Aux objectives follow Switch/GShard: load-balance loss (makes routing
uniform so EP shards stay busy) and router z-loss (keeps logits small for
bf16 stability); both are sown into an ``aux_loss`` collection that
``MoeLmTask`` folds into the training loss.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.runtime import compat
from tensorflow_train_distributed_tpu.models import layers as L
from tensorflow_train_distributed_tpu.ops.losses import (
    fold_sample_weight, softmax_cross_entropy,
)


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    vocab_size: int = 32_000
    d_model: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = 8
    ffn_size: int = 14_336
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 1          # 1 = every layer MoE (Mixtral); 2 = alternate
    max_positions: int = 4096
    rope_base: float = 10_000.0
    rms_epsilon: float = 1e-5
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3
    dtype: object = jnp.bfloat16
    remat: bool = True
    # Expert-compute formulation.  "dense": GShard dispatch/combine
    # einsums — capacity-bounded, static-shaped, and the EP-sharded path
    # (GSPMD turns the einsums into all-to-alls under an ``expert``
    # mesh axis).  "gmm": MegaBlocks-style DROPLESS dispatch — tokens
    # are sorted by expert and the three FFN matmuls run as megablox
    # grouped matmuls (``jax.experimental.pallas.ops.tpu.megablox``),
    # skipping the dispatch-einsum FLOPs and the capacity padding
    # entirely (capacity_factor is ignored; nothing is ever dropped).
    # Same parameter tree either way, so checkpoints transfer between
    # formulations.  Under an ``expert``-sharded mesh the gmm path runs
    # the shard_map expert-parallel formulation (local sort +
    # group_offset gmm + one psum); unsharded it is the single-chip
    # throughput path.
    dispatch: str = "dense"
    # DeepSeek/Qwen-MoE-style shared expert: a dense SwiGLU FFN of this
    # hidden size runs on EVERY token beside the routed experts, outputs
    # summed.  Routing pressure drops (common knowledge lives in the
    # shared path; routed experts specialize) at a fixed dense-FLOP
    # cost.  Orthogonal to dispatch ("dense"/"gmm"), decode, serving and
    # EP sharding — the branch is an ordinary tensor-shardable MLP.
    # None = plain Mixtral-style (no shared expert).
    shared_expert_size: Optional[int] = None
    # Qwen-MoE-style scalar gate on the shared branch:
    # sigmoid(x @ w_gate) per token multiplies the shared output
    # (needs shared_expert_size).
    shared_expert_gate: bool = False
    # Renormalize the top-k gates over the chosen experts (GShard /
    # Mixtral rule).  False = raw softmax probabilities as gates —
    # the Qwen2-MoE default (norm_topk_prob=False).
    norm_topk_prob: bool = True
    # q/k/v projection biases (Qwen attention convention; out stays
    # unbiased) — layers.MultiHeadAttention.qkv_bias.
    qkv_bias: bool = False


MOE_PRESETS = {
    # Mixtral-8x7B-shaped flagship EP config.
    "mixtral_8x7b": MoeConfig(),
    "moe_1b": MoeConfig(d_model=1024, num_layers=8, num_heads=16,
                        num_kv_heads=4, ffn_size=4096, num_experts=8),
    # Single-16GiB-chip bench point (~370M total / ~135M active params):
    # the EP family's silicon number (tools/bench_moe.py).
    "moe_370m": MoeConfig(d_model=768, num_layers=8, num_heads=12,
                          num_kv_heads=4, ffn_size=2048, num_experts=8,
                          top_k=2, max_positions=2048),
    "moe_tiny": MoeConfig(vocab_size=256, d_model=64, num_layers=2,
                          num_heads=4, num_kv_heads=2, ffn_size=128,
                          num_experts=4, top_k=2, max_positions=128,
                          dtype=jnp.float32, remat=False),
    # Qwen1.5-MoE-A2.7B shape (14.3B total / 2.7B active): the gated-
    # shared-expert flagship — fine-grained 60-expert top-4 routing,
    # raw softmax gates, qkv biases; --init-from-hf a local checkpoint.
    "qwen15_moe_a27b": MoeConfig(
        vocab_size=151_936, d_model=2048, num_layers=24, num_heads=16,
        num_kv_heads=16, ffn_size=1408, num_experts=60, top_k=4,
        capacity_factor=15.0,  # E/k — the no-drop HF-parity setting
        max_positions=8192, rope_base=1_000_000.0,
        rms_epsilon=1e-6,
        shared_expert_size=5632, shared_expert_gate=True,
        norm_topk_prob=False, qkv_bias=True),
    # DeepSeek/Qwen-MoE-style: always-on shared expert beside the
    # routed ones (tiny test shape).
    "moe_tiny_shared": MoeConfig(vocab_size=256, d_model=64,
                                 num_layers=2, num_heads=4,
                                 num_kv_heads=2, ffn_size=128,
                                 num_experts=4, top_k=2,
                                 max_positions=128, dtype=jnp.float32,
                                 remat=False, shared_expert_size=96),
    # Full Qwen-convention tiny shape (gated shared expert, qkv biases,
    # raw top-k gates) — matches the test HF fixture for the CLI
    # --init-from-hf path.
    "qwen_moe_tiny": MoeConfig(vocab_size=256, d_model=64,
                               num_layers=2, num_heads=4,
                               num_kv_heads=2, ffn_size=96,
                               num_experts=4, top_k=2,
                               capacity_factor=2.0,
                               max_positions=128, dtype=jnp.float32,
                               remat=False, shared_expert_size=112,
                               shared_expert_gate=True,
                               norm_topk_prob=False, qkv_bias=True),
}


def _router_one_hot(probs: jax.Array, top_k: int, capacity: int,
                    normalize: bool = True):
    """Top-k dispatch/combine tensors with per-expert capacity.

    ``probs`` [T, E] float32.  Returns ``dispatch`` [T, E, C] one-hot and
    ``combine`` [T, E, C] gate-weighted, plus the [T, E] routed mask for
    the load-balance loss.  Tokens beyond an expert's capacity are dropped
    (their combine weight is zero → they ride the residual path).
    ``normalize=False`` keeps raw softmax probabilities as gates (the
    Qwen2-MoE ``norm_topk_prob=False`` convention) instead of the GShard
    renormalize-over-chosen rule.
    """
    tokens, num_experts = probs.shape
    remaining = probs
    fill = jnp.zeros((num_experts,), jnp.int32)  # tokens already assigned
    dispatch = jnp.zeros((tokens, num_experts, capacity), probs.dtype)
    combine = jnp.zeros((tokens, num_experts, capacity), probs.dtype)
    routed = jnp.zeros((tokens, num_experts), probs.dtype)
    gate_sum = jnp.zeros((tokens, 1), probs.dtype)
    for _ in range(top_k):  # static, small
        idx = jnp.argmax(remaining, axis=-1)                      # [T]
        onehot = jax.nn.one_hot(idx, num_experts, dtype=probs.dtype)
        gate = jnp.sum(remaining * onehot, axis=-1, keepdims=True)  # [T,1]
        # Position of each token within its expert's buffer this round,
        # offset by what previous rounds already filled.
        pos = jnp.cumsum(onehot, axis=0) - onehot + fill[None, :]   # [T,E]
        pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [T]
        keep = (pos_tok < capacity).astype(probs.dtype)             # [T]
        slot = jax.nn.one_hot(pos_tok, capacity, dtype=probs.dtype)
        hot = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + hot
        combine = combine + hot * gate[:, :, None]
        routed = routed + onehot * keep[:, None]
        gate_sum = gate_sum + gate * keep[:, None]
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0).astype(
            jnp.int32)
        remaining = remaining * (1.0 - onehot)
    if normalize:
        # Over the chosen experts (GShard top-2 rule).
        combine = combine / jnp.maximum(gate_sum[:, :, None], 1e-9)
    return dispatch, combine, routed


class _ExpertFfn(nn.Module):
    """One expert's SwiGLU FFN over its [groups, capacity, d_model] buffer.

    Separate from ``layers.MlpBlock`` because expert buffers carry
    (group, capacity, embed) dims — the shared block's (batch, length, ·)
    activation constraints don't apply.  ``nn.vmap`` stacks this over the expert axis,
    tagging params with the ``expert`` logical name.
    """

    hidden: int
    dtype: object

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        gate = L.dense(self.hidden, ("embed", "mlp"), use_bias=False,
                       dtype=self.dtype, name="wi_gate")(x)
        up = L.dense(self.hidden, ("embed", "mlp"), use_bias=False,
                     dtype=self.dtype, name="wi_up")(x)
        h = nn.silu(gate) * up
        return L.dense(d, ("mlp", "embed"), use_bias=False,
                       dtype=self.dtype, name="wo")(h)


class _StackedKernel(nn.Module):
    """One expert-stacked ``[num_experts, ...]`` kernel parameter.

    Exists to give the gmm dispatch path the SAME parameter tree as the
    dense path's ``nn.vmap(_ExpertFfn)`` — ``experts/<name>/kernel``,
    expert-stacked, logical axes ``("expert", ...)`` — so checkpoints
    transfer freely between the two formulations.  ``batch_axis=(0,)``
    keeps per-expert init statistics identical to the vmap'd per-expert
    lecun_normal (without it the expert axis would inflate fan_in).
    """

    shape: tuple
    logical_axes: tuple

    @nn.compact
    def __call__(self):
        if self.has_variable("quant", "scale"):
            # The int8 serving path rewrites nn.Dense call sites via a
            # method interceptor (models/quant.py) — this raw-param read
            # would cast int8 CODES to bf16 with no scale applied and
            # produce garbage silently.
            raise NotImplementedError(
                "int8 weight-only serving is not wired for the gmm "
                "dispatch path — serve quantized MoE checkpoints with "
                "dispatch='dense', or dequantize_params() first")
        return self.param(
            "kernel",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(batch_axis=(0,)),
                self.logical_axes),
            self.shape)


def _gmm(lhs, rhs, group_sizes, interpret, group_offset=None):
    """Megablox grouped matmul: rows of ``lhs`` hit the ``rhs`` slice of
    their group (``group_sizes`` [E] row counts, summing to lhs rows).

    ``ops.gmm`` is the differentiable (custom-VJP) wrapper — the
    backward pass runs as grouped matmuls too.  ``interpret`` runs the
    kernel in pallas interpret mode for CPU tests.  ``group_offset``
    (expert parallelism): ``rhs`` holds only groups
    [offset, offset + rhs.shape[0]) and rows outside them come back
    ZERO — verified: per-shard outputs sum exactly to the full gmm, and
    grads flow only through the shard's own rows.
    """
    from jax.experimental.pallas.ops.tpu.megablox import ops as _mb

    return _mb.gmm(lhs, rhs, group_sizes,
                   preferred_element_type=jnp.float32, interpret=interpret,
                   group_offset=None if group_offset is None
                   else jnp.asarray(group_offset, jnp.int32))


def _routed_ffn_rows(flat, top_e, gate_w, num_experts, wi_gate, wi_up,
                     wo, *, dtype, interpret, group_offset=None,
                     psum_axis=None):
    """The dropless routed FFN over a block of tokens.

    ``flat`` [T, D] tokens; ``top_e``/``gate_w`` [T, k] the router's
    expert choices and normalized gates (computed ONCE by the caller —
    under EP they ride into the shard_map rather than being recomputed
    per expert shard).  Sort token copies by expert, run the SwiGLU as
    grouped matmuls, unsort and gate-combine.  With
    ``group_offset``/``psum_axis`` set this is the per-shard body of
    the expert-parallel formulation: each expert shard computes ONLY
    its experts' rows (zeros elsewhere) and the psum over the expert
    axis assembles the full row set — every row is computed by exactly
    one shard, so the sum is exact, not averaged.
    """
    t, d = flat.shape
    top_k = top_e.shape[-1]
    e_total = num_experts
    e_flat = top_e.reshape(-1)                          # [T*k] token-major
    order = jnp.argsort(e_flat)                         # stable
    xs = jnp.take(flat, order // top_k, axis=0).astype(dtype)
    sizes = jnp.bincount(e_flat, length=e_total).astype(jnp.int32)
    m = t * top_k
    m_pad = -(-m // 128) * 128                          # kernel row tile
    if m_pad != m:
        # Zero rows appended to the LAST expert's range: zero inputs
        # produce zero outputs (silu(0)*0 = 0), then sliced off before
        # the combine — never observable, under EP included (the last
        # shard computes them as zeros; psum adds zeros).
        xs = jnp.pad(xs, ((0, m_pad - m), (0, 0)))
        sizes = sizes.at[e_total - 1].add(m_pad - m)
    gate = _gmm(xs, wi_gate, sizes, interpret, group_offset)
    up = _gmm(xs, wi_up, sizes, interpret, group_offset)
    h = (nn.silu(gate) * up).astype(dtype)
    out = _gmm(h, wo, sizes, interpret, group_offset)   # [m_pad, D] f32
    if psum_axis is not None:
        out = jax.lax.psum(out, psum_axis)
    inv = jnp.zeros((m,), jnp.int32).at[order].set(
        jnp.arange(m, dtype=jnp.int32))
    y = jnp.take(out[:m], inv, axis=0).reshape(t, top_k, d)
    return jnp.sum(y * gate_w[..., None], axis=1).astype(dtype)


class _GmmExperts(nn.Module):
    """Dropless expert FFN: grouped matmuls over expert-sorted rows.

    ``flat`` [T, d_model] tokens, ``p2`` [T, E] router probs; same
    SwiGLU math as ``_ExpertFfn``, with the three matmuls as
    ``megablox.gmm`` so each expert's rows hit its own kernel slice
    without ``[E, capacity]`` buffers or dispatch one-hots.

    With ``ep_mesh`` (an ambient mesh whose ``expert`` axis > 1) the
    compute runs as a ``shard_map``: tokens stay sharded over the data
    axes (each data shard sorts ITS tokens locally), expert kernels
    shard over ``expert``, each expert shard computes only its experts'
    rows via ``group_offset``, and one psum over ``expert`` assembles
    the rows — dropless expert parallelism with exactly one collective
    pair (tokens broadcast over the expert axis on the way in, psum on
    the way out).
    """

    num_experts: int
    hidden: int
    dtype: object

    @nn.compact
    def __call__(self, flat, top_e, gate_w, *, interpret, ep_mesh=None):
        d = flat.shape[-1]
        e, f = self.num_experts, self.hidden
        wi_gate = _StackedKernel((e, d, f), ("expert", "embed", "mlp"),
                                 name="wi_gate")().astype(self.dtype)
        wi_up = _StackedKernel((e, d, f), ("expert", "embed", "mlp"),
                               name="wi_up")().astype(self.dtype)
        wo = _StackedKernel((e, f, d), ("expert", "mlp", "embed"),
                            name="wo")().astype(self.dtype)
        if ep_mesh is None:
            return _routed_ffn_rows(
                flat, top_e, gate_w, e, wi_gate, wi_up, wo,
                dtype=self.dtype, interpret=interpret)

        from tensorflow_train_distributed_tpu.runtime.compat import (
            shard_map,
        )
        from jax.sharding import PartitionSpec as P

        from tensorflow_train_distributed_tpu.runtime.mesh import (
            batch_axes,
        )

        local_e = e // ep_mesh.shape["expert"]
        bspec = batch_axes(ep_mesh)
        dtype_, interp_ = self.dtype, interpret

        def body(flat_b, te_b, gw_b, wg_b, wu_b, wo_b):
            e0 = jax.lax.axis_index("expert") * local_e
            return _routed_ffn_rows(
                flat_b, te_b, gw_b, e, wg_b, wu_b, wo_b,
                dtype=dtype_, interpret=interp_, group_offset=e0,
                psum_axis="expert")

        return shard_map(
            body, mesh=ep_mesh,
            in_specs=(P(bspec, None), P(bspec, None), P(bspec, None),
                      P("expert", None, None), P("expert", None, None),
                      P("expert", None, None)),
            out_specs=P(bspec, None), check_vma=False,
        )(flat, top_e, gate_w, wi_gate, wi_up, wo)


class MoEMlpBlock(nn.Module):
    """Routed expert FFN, a drop-in for ``layers.MlpBlock``."""

    config: MoeConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # GShard grouping: each sequence is a routing group, so dispatch
        # tensors are [G, S, E, C] with per-group capacity C ≈ S·k·cf/E —
        # cost linear in total tokens (an ungrouped [T, E, C] formulation
        # would be O(T²) and serialize the position cumsum across data
        # shards).  Groups ride the batch sharding; routing is per-group
        # independent, so no cross-shard bookkeeping exists at all.
        x = nn.with_logical_constraint(x, ("batch", "length", "embed"))
        groups, group_size, d_model = x.shape

        # Router in float32: small matmul, numerically load-bearing.
        logits = L.dense(cfg.num_experts, ("embed", "expert"),
                         use_bias=False, dtype=jnp.float32,
                         name="router")(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)          # [G, S, E]
        if cfg.dispatch == "gmm":
            return self._add_shared(x, self._gmm_moe(x, logits, probs))
        if cfg.dispatch != "dense":
            raise ValueError(
                f"unknown MoeConfig.dispatch {cfg.dispatch!r} "
                "(expected 'dense' or 'gmm')")
        capacity = max(
            1, int(cfg.capacity_factor * cfg.top_k * group_size
                   / cfg.num_experts))
        dispatch, combine, routed = jax.vmap(
            lambda p: _router_one_hot(p, cfg.top_k, capacity,
                                      cfg.norm_topk_prob))(probs)

        # Aux losses (Switch §4 / ST-MoE): sown, folded in by the task.
        frac_routed = jnp.mean(routed, axis=(0, 1))      # [E] token fraction
        frac_prob = jnp.mean(probs, axis=(0, 1))         # [E] router mass
        lb = cfg.num_experts * jnp.sum(frac_routed * frac_prob) / cfg.top_k
        z = jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
        self.sow("aux_loss", "load_balance", cfg.aux_loss_weight * lb)
        self.sow("aux_loss", "router_z", cfg.z_loss_weight * z)

        # Routing health (sown separately — diagnostics, NOT loss terms):
        # a binding capacity_factor drops tokens silently (they ride the
        # residual), which also breaks packed==lone-document parity (see
        # MoeLmModel packing note).  dropped_frac = fraction of desired
        # top_k assignments that hit a full expert; expert_load = each
        # expert's share of kept tokens (uniform = 1/E).
        desired = jnp.asarray(groups * group_size * cfg.top_k, jnp.float32)
        self.sow("router_stats", "dropped_frac",
                 1.0 - jnp.sum(routed) / desired)
        self.sow("router_stats", "expert_load",
                 jnp.sum(routed, axis=(0, 1)) / jnp.maximum(
                     jnp.sum(routed), 1.0))

        dispatch = dispatch.astype(cfg.dtype)
        expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, x)
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", "batch", None, "embed"))
        experts = nn.vmap(
            _ExpertFfn,
            in_axes=0, out_axes=0,
            # "quant": expert-stacked int8 serving scales (models.quant)
            # slice per-expert like the stacked kernels they mirror, so
            # the fused int8 Dense path is exact for MoE too.
            variable_axes={"params": 0, "quant": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(hidden=cfg.ffn_size, dtype=cfg.dtype, name="experts")
        expert_out = experts(expert_in)                  # [E, G, C, D]
        expert_out = nn.with_logical_constraint(
            expert_out, ("expert", "batch", None, "embed"))
        y = jnp.einsum("gsec,egcd->gsd", combine.astype(cfg.dtype),
                       expert_out)
        y = nn.with_logical_constraint(y, ("batch", "length", "embed"))
        return self._add_shared(x, y)

    def _add_shared(self, x, routed):
        """Shared-expert branch (``shared_expert_size``): an always-on
        SwiGLU over every token, summed with the routed output.  A
        plain ``layers.MlpBlock``, so it tensor-shards/quantizes/decodes
        like any dense FFN; identity when the config leaves it None."""
        cfg = self.config
        if not cfg.shared_expert_size:
            return routed
        shared = L.MlpBlock(hidden=cfg.shared_expert_size,
                            dtype=cfg.dtype, gated=True,
                            activation=nn.silu,  # SwiGLU, like every
                            name="shared_mlp")(x)   # gated FFN here
        if cfg.shared_expert_gate:
            # Qwen-MoE: one sigmoid scalar per token scales the shared
            # branch (f32 like the router — small and load-bearing).
            g = jax.nn.sigmoid(L.dense(
                1, ("embed", None), use_bias=False, dtype=jnp.float32,
                name="shared_gate")(x.astype(jnp.float32)))
            shared = shared * g.astype(shared.dtype)
        return nn.with_logical_constraint(
            routed + shared, ("batch", "length", "embed"))

    def _gmm_moe(self, x, logits, probs):
        """Dropless dispatch (MegaBlocks, arXiv:2211.15841): sort token
        copies by expert, run the FFN as grouped matmuls.

        No capacity, no drops — every top-k assignment is computed, so
        ``capacity_factor`` is ignored and packed==lone-document parity
        holds unconditionally (the dense path's binding-capacity caveat
        does not exist here).  Output matches the dense path exactly
        whenever the dense path drops nothing.
        """
        cfg = self.config
        groups, group_size, d_model = x.shape
        n_tokens = groups * group_size
        k = cfg.top_k
        flat = x.reshape(n_tokens, d_model)
        p2 = probs.reshape(n_tokens, cfg.num_experts)
        top_p, top_e = jax.lax.top_k(p2, k)              # [T, k]
        # GShard top-k gate rule: normalize over the chosen experts.
        # (The dense path normalizes over *kept* gates — identical here
        # because nothing is ever dropped.)  Computed ONCE; under EP it
        # rides into the shard_map instead of re-running per shard.
        if cfg.norm_topk_prob:
            gate_w = top_p / jnp.maximum(
                jnp.sum(top_p, axis=-1, keepdims=True), 1e-9)
        else:
            gate_w = top_p    # raw softmax gates (Qwen2-MoE rule)

        # Aux losses — same definitions as the dense path, with
        # routed = all top-k assignments (dropless).
        routed = jnp.sum(jax.nn.one_hot(top_e, cfg.num_experts,
                                        dtype=jnp.float32), axis=1)
        lb = cfg.num_experts * jnp.sum(
            jnp.mean(routed, axis=0) * jnp.mean(p2, axis=0)) / k
        z = jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
        self.sow("aux_loss", "load_balance", cfg.aux_loss_weight * lb)
        self.sow("aux_loss", "router_z", cfg.z_loss_weight * z)
        self.sow("router_stats", "dropped_frac", jnp.zeros((), jnp.float32))
        self.sow("router_stats", "expert_load",
                 jnp.sum(routed, axis=0) / float(n_tokens * k))

        # Expert parallelism: an ambient mesh with an ``expert`` axis
        # routes the compute through the shard_map formulation (each
        # data shard sorts locally, each expert shard computes its own
        # experts via group_offset, one psum assembles).
        mesh = compat.get_abstract_mesh()
        ep_mesh = None
        if (mesh is not None and not mesh.empty
                and mesh.shape.get("expert", 1) > 1):
            if cfg.num_experts % mesh.shape["expert"]:
                raise ValueError(
                    f"num_experts={cfg.num_experts} not divisible by the "
                    f"expert mesh axis ({mesh.shape['expert']})")
            if mesh.shape.get("tensor", 1) > 1:
                # The shard_map body replicates expert kernels over the
                # tensor axis (its in_specs only mention expert/data) —
                # silently undoing TP would blow per-device memory and
                # duplicate FLOPs.  The dense dispatch keeps full
                # expert×tensor GSPMD sharding; refuse loudly here.
                raise ValueError(
                    "dispatch='gmm' supports data×fsdp×expert meshes; "
                    "an expert×tensor mesh keeps dispatch='dense' "
                    "(GSPMD shards both axes there)")
            ep_mesh = mesh
        y = _GmmExperts(num_experts=cfg.num_experts, hidden=cfg.ffn_size,
                        dtype=cfg.dtype, name="experts")(
            flat, top_e, gate_w,
            interpret=jax.default_backend() != "tpu", ep_mesh=ep_mesh)
        return nn.with_logical_constraint(
            y.reshape(groups, group_size, d_model),
            ("batch", "length", "embed"))


class MoeDecoderBlock(nn.Module):
    config: MoeConfig
    use_moe: bool = True
    # Autoregressive decode (models.generate): KV-cached attention; the
    # MoE dispatch needs nothing special — at q_len 1 each group holds
    # one token, capacity is >= 1 per expert, so routing never drops.
    decode: bool = False
    cache_len: int = 0
    slot_decode: bool = False
    # Paged serving KV cache — see layers.MultiHeadAttention.
    paged_kv_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, x, segment_ids=None, positions=None):
        cfg = self.config
        h = L.RMSNorm(epsilon=cfg.rms_epsilon, dtype=cfg.dtype,
                      name="attn_norm")(x)
        x = x + L.MultiHeadAttention(
            qkv_bias=cfg.qkv_bias,
            num_heads=cfg.num_heads,
            head_dim=cfg.d_model // cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            dtype=cfg.dtype, causal=True, use_rope=True,
            rope_base=cfg.rope_base, name="attention",
            decode=self.decode,
            cache_len=self.cache_len or cfg.max_positions,
            slot_decode=self.slot_decode,
            paged_kv_blocks=self.paged_kv_blocks,
            kv_block_size=self.kv_block_size,
        )(h, segment_ids=segment_ids, positions=positions)
        h = L.RMSNorm(epsilon=cfg.rms_epsilon, dtype=cfg.dtype,
                      name="mlp_norm")(x)
        if self.use_moe:
            x = x + MoEMlpBlock(cfg, name="moe")(h)
        else:
            x = x + L.MlpBlock(hidden=cfg.ffn_size, dtype=cfg.dtype,
                               activation=nn.silu, gated=True,
                               name="mlp")(h)
        return x


class MoeLmModel(nn.Module):
    """Decoder LM with MoE FFNs every ``moe_every``-th layer.

    Layers are a Python loop (not depth-scan): MoE layers interleave with
    dense ones, so blocks are not homogeneous when ``moe_every > 1``.
    """

    config: MoeConfig = MoeConfig()
    # models.generate contract (same as LlamaModel): decode=True adds
    # the mutable "cache" collection, sized by cache_len.  Decode routes
    # each step as a one-token group, so capacity NEVER binds there —
    # cached decode equals the training-time forward exactly only while
    # the training capacity doesn't bind either (the Mixtral-import E/k
    # default guarantees that; a binding capacity_factor makes the
    # full-sequence forward drop tokens decode would not, the same
    # caveat as packed segments above).
    decode: bool = False
    cache_len: int = 0
    # Per-slot cache positions (continuous-batching serving,
    # serving.ServingEngine) — see layers.MultiHeadAttention.slot_decode.
    slot_decode: bool = False
    # Paged serving KV cache — see layers.MultiHeadAttention.
    paged_kv_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, tokens, *, segment_ids=None, positions=None):
        cfg = self.config
        if segment_ids is not None and self.decode:
            raise ValueError("decode mode does not take packed segments")
        if segment_ids is not None and positions is None:
            # Packed rows (llama-path contract): segment-masked attention
            # + RoPE positions restarting at each document boundary.
            # Routing needs no masking — it is per-token, and within a
            # group earlier tokens' dispatch slots are unaffected by later
            # ones (the capacity cumsum is causal in token order).  The
            # packed == lone-document equivalence is exact only while no
            # capacity drops occur: under a binding capacity_factor,
            # earlier documents consume a shared per-row budget, so later
            # documents may see drops (residual fallthrough) they would
            # not see alone.
            from tensorflow_train_distributed_tpu.models.llama import (
                segment_relative_positions,
            )

            positions = segment_relative_positions(segment_ids)
        x = L.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                    name="token_embed")(tokens)
        for i in range(cfg.num_layers):
            blk = MoeDecoderBlock
            if cfg.remat and not self.decode:
                # No backward in decode, and KV-cache writes must not
                # replay under a checkpoint.
                blk = nn.remat(blk, prevent_cse=False)
            x = blk(cfg, use_moe=(i % cfg.moe_every == 0),
                    decode=self.decode, cache_len=self.cache_len,
                    slot_decode=self.slot_decode,
                    paged_kv_blocks=self.paged_kv_blocks,
                    kv_block_size=self.kv_block_size,
                    name=f"layer_{i}")(x, segment_ids, positions)
        x = L.RMSNorm(epsilon=cfg.rms_epsilon, dtype=cfg.dtype,
                      name="final_norm")(x)
        logits = L.dense(cfg.vocab_size, ("embed", "vocab"), use_bias=False,
                         dtype=cfg.dtype, name="lm_head")(x)
        return nn.with_logical_constraint(
            logits, ("batch", "length", "vocab"))


def _sown_values(collection, name: str) -> list:
    """All leaves sown under ``name`` anywhere in a (nested) flax
    collection — one entry per MoE layer.  Path-based so dict and
    FrozenDict collections (flax_return_frozendict mode) both work."""
    return [leaf for path, leaf
            in jax.tree_util.tree_leaves_with_path(collection)
            if any(getattr(p, "key", None) == name for p in path)]


def _routing_metrics(stats: dict) -> dict:
    """Scalar routing-health metrics averaged over MoE layers.

    ``dropped_frac`` > 0 means the capacity_factor is binding — tokens
    are silently falling through the residual AND packed rows are no
    longer exactly equivalent to lone documents; ``expert_load_max/min``
    bound the per-expert share of kept tokens (uniform = 1/E), exposing
    hot/cold experts that an aggregate load-balance loss value hides.
    """
    dropped = _sown_values(stats, "dropped_frac")
    load = _sown_values(stats, "expert_load")
    if not dropped:
        return {}
    mean_load = jnp.mean(jnp.stack(load), axis=0)  # [E] over layers
    return {
        "dropped_frac": jnp.mean(jnp.stack(dropped)),
        "expert_load_max": jnp.max(mean_load),
        "expert_load_min": jnp.min(mean_load),
    }


class MoeLmTask:
    """Causal LM objective + routed aux losses."""

    def __init__(self, config: MoeConfig = MoeConfig()):
        self.config = config
        self.model = MoeLmModel(config)

    def init_variables(self, rng, batch):
        variables = dict(self.model.init(rng, batch["tokens"]))
        # Ephemeral sown collections, not trainable state.
        variables.pop("aux_loss", None)
        variables.pop("router_stats", None)
        return variables

    def loss_fn(self, params, model_state, batch, rng, train):
        del rng
        logits, collections = self.model.apply(
            {"params": params}, batch["tokens"],
            segment_ids=batch.get("segment_ids"),
            mutable=["aux_loss", "router_stats"])
        logits = logits.astype(jnp.float32)
        weights = fold_sample_weight(batch, batch["targets"].shape,
                                     batch.get("loss_weights"))
        ce, acc = softmax_cross_entropy(logits, batch["targets"],
                                        weights=weights)
        aux = sum(
            jnp.sum(jnp.asarray(v))
            for v in jax.tree.leaves(collections.get("aux_loss", {})))
        # Aux terms are training regularizers computed over every routed
        # token — including eval pad rows, which fold_sample_weight cannot
        # mask (they bypass the CE weights).  Excluding them from the eval
        # loss keeps the padded-eval exactness contract: eval 'loss' is
        # the pad-exact CE, aux stays visible as a diagnostic metric.
        loss = ce + aux if train else ce
        metrics = {"accuracy": acc, "ce_loss": ce,
                   "aux_loss": jnp.asarray(aux)}
        metrics.update(_routing_metrics(collections.get("router_stats", {})))
        if weights is not None:
            metrics["loss_weight"] = weights.sum()
        return loss, (metrics, model_state)


def make_task(config: MoeConfig = MOE_PRESETS["mixtral_8x7b"]) -> MoeLmTask:
    return MoeLmTask(config)
