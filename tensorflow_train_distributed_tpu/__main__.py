"""``python -m tensorflow_train_distributed_tpu`` → the launcher."""

from tensorflow_train_distributed_tpu.launch import main

if __name__ == "__main__":
    raise SystemExit(main())
