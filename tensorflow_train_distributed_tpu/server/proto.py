"""Length-prefixed frame protocol between the gateway and its
subprocess replica workers.

One frame = a 4-byte big-endian unsigned payload length, then the
payload: 1 type byte + a UTF-8 JSON object.  The length prefix is the
whole framing story — no delimiters in the payload, no resync
heuristics: a reader either gets a complete frame or a
``ProtocolError``, and a bounded ``max_frame`` means a corrupt or
hostile length prefix can never make the parent allocate or block on
an unbounded read.  JSON bodies keep every frame printable in a log
line while the framing itself stays binary (token-id lists are small;
the one exception is ``KV_HANDOFF``, the reserved binary payload type:
its payload is ``type byte + 4-byte header length + JSON header + raw
row bytes``, so quantized KV pool rows ship verbatim without a base64
detour — the JSON header still makes the frame log-printable).

The stream is VERSIONED at the hello: the worker's first frame is
``HELLO`` carrying ``proto=PROTO_VERSION`` plus the engine's static
shape (slots, cache_len, paged-pool geometry) — a parent that sees any
other version (or any other first frame) fails that one replica with a
classified ``ProtocolError`` instead of guessing at field meanings.

Frame types (direction):

- ``HELLO``   worker → parent: version, pid, engine info, clock anchor.
- ``SUBMIT``  parent → worker: request id, prompt, max_new, seed,
  deadline, ``resume_from`` (the failover re-admission contract rides
  the protocol unchanged — the resumed tail is part of the prompt).
- ``CHUNK``   worker → parent: newly committed generated tokens.
- ``RETIRE``  worker → parent: terminal status
  (``ok|expired|invalid|error``) + error text.
- ``CANCEL``  parent → worker: collapse one request's deadline
  (streaming client went away).
- ``DRAIN``   parent → worker: stop admitting, finish in-flight, send
  ``BYE``, exit.
- ``STATS``   worker → parent: the heartbeat — queue/slot occupancy,
  kv gauges, rss bytes, step progress (the hung-dispatch watchdog's
  feed), and a batch of relayed flight-recorder events.
- ``BYE``     worker → parent: drain complete, exiting cleanly.
- ``DIED``    worker → parent: the worker's driver loop died with
  error propagation (the corpse the parent's ``failure()`` reports).
- ``PREFILL`` parent → prefill worker: run the staged per-piece
  prefill for one prompt and export the finished KV (disaggregated
  serving — the worker answers with a ``KV_HANDOFF`` or a ``KV_ACK``
  carrying the refusal).
- ``KV_HANDOFF``  the binary frame, both directions: prefill worker →
  parent carries the exported block rows; parent → decode worker
  carries the same bytes for installation.  Header keys: request id,
  token ids, leaf manifest (path/dtype/shape per pool leaf); the blob
  is the concatenated row bytes, bit-identical to the pool contents.
- ``KV_ACK``  worker → parent: terminal answer to ``PREFILL`` (export
  refused) or ``KV_HANDOFF`` (rows installed / install skipped), with
  the matched-token count so routing knows how warm the prefix is.
- ``MIGRATE``  the second binary frame, both directions: live
  mid-stream request migration.  A header with ``op="export"`` (empty
  blob) asks the worker to serialize one live lane — KV block rows
  (the exact ``KV_HANDOFF`` byte recipe), generated-token history,
  rng counter, staged-prefill cursor — which comes back as a MIGRATE
  whose header is the lane manifest and whose blob is the row bytes;
  the parent forwards that frame to the target worker (``op`` absent)
  for installation, answered by a ``KV_ACK`` with the warm-token
  count.  Every header carries ``v=MIGRATE_VERSION``: a mismatch is a
  classified ``ProtocolError`` that fails ONE replica, and a worker
  that predates the frame ignores it (the exchange times out into the
  resume-from-token failover fallback — no migration is ever
  load-bearing for correctness).
- ``PING``  parent → worker / ``PONG``  worker → parent: the NTP-style
  clock-sync exchange.  A PING carries the parent's monotonic send
  stamp ``t``; the worker echoes it back in a PONG together with its
  own monotonic ``mono`` stamped at the reply.  The parent computes
  ``rtt = t_recv - t`` and the midpoint offset estimate
  ``(t + t_recv)/2 - mono`` whose error is bounded by ``rtt/2`` —
  min-RTT samples replace the HELLO's one-way offset guess, which
  silently absorbs the full transport latency.  Both frames are
  stateless (the worker keeps nothing, the parent needs no pending
  table) and OPTIONAL: a worker that predates them ignores PING, the
  parent keeps the HELLO offset — no version bump.

Everything here is pure framing — no sockets are owned, no threads
are spawned: ``read_frame``/``write_frame`` work over any file-like
byte stream (the pool uses a ``socketpair`` so a stray ``print`` in
the child can never corrupt the stream the way stdout piping would),
and ``FrameSender`` is the one locked writer both sides share so
frames from concurrent threads never interleave mid-frame.
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Optional, Tuple

#: Bumped whenever a frame's meaning changes; the HELLO handshake
#: refuses mismatches (a half-upgraded fleet must fail one replica
#: loudly, not misparse frames quietly).
PROTO_VERSION = 1

#: Per-frame payload bound: bigger than any real frame (token chunks
#: are tens of ids; stats batches are capped) by orders of magnitude,
#: small enough that a corrupt length prefix cannot balloon a read.
MAX_FRAME_BYTES = 4 << 20

_HEADER = struct.Struct("!I")

# Frame type bytes.
HELLO = 1
SUBMIT = 2
CHUNK = 3
RETIRE = 4
CANCEL = 5
DRAIN = 6
STATS = 7
BYE = 8
DIED = 9
PREFILL = 10
KV_HANDOFF = 11
KV_ACK = 12
MIGRATE = 13
PING = 14
PONG = 15

FRAME_NAMES = {
    HELLO: "HELLO", SUBMIT: "SUBMIT", CHUNK: "CHUNK", RETIRE: "RETIRE",
    CANCEL: "CANCEL", DRAIN: "DRAIN", STATS: "STATS", BYE: "BYE",
    DIED: "DIED", PREFILL: "PREFILL", KV_HANDOFF: "KV_HANDOFF",
    KV_ACK: "KV_ACK", MIGRATE: "MIGRATE", PING: "PING", PONG: "PONG",
}

#: Frame types whose payload is ``type byte + 4-byte header length +
#: JSON header + raw bytes`` instead of pure JSON.  ``read_frame``
#: surfaces the raw bytes under the reserved body key ``"blob"``.
BINARY_FRAMES = frozenset({KV_HANDOFF, MIGRATE})

#: MIGRATE manifest version, carried as ``v`` in every MIGRATE header
#: (requests AND payloads).  Orthogonal to ``PROTO_VERSION``: the lane
#: manifest can evolve (new state fields) without re-versioning the
#: whole stream, but a mismatched manifest must still fail ONE replica
#: loudly — installing a misread lane would corrupt a live stream,
#: which is strictly worse than the failover fallback.
MIGRATE_VERSION = 1

#: The body key binary frames deliver their raw bytes under (reserved:
#: a JSON header may not use it).
BLOB_KEY = "blob"


class ProtocolError(RuntimeError):
    """The frame stream is unusable (truncated frame, oversized length
    prefix, non-JSON payload, version mismatch).  Always fails exactly
    ONE replica: the parent classifies the reason into that replica's
    health state and SIGKILLs the worker — it never propagates past
    the replica boundary."""


def encode_frame(ftype: int, body: dict,
                 max_frame: int = MAX_FRAME_BYTES) -> bytes:
    """One wire-ready frame: header + type byte + compact JSON."""
    if ftype in BINARY_FRAMES:
        raise ProtocolError(
            f"{FRAME_NAMES.get(ftype, ftype)} is a binary frame type; "
            "encode it with encode_binary_frame (a JSON-encoded body "
            "would be mis-parsed as a binary layout on the far side)")
    payload = bytes([ftype]) + json.dumps(
        body, separators=(",", ":")).encode()
    if len(payload) > max_frame:
        raise ProtocolError(
            f"outgoing {FRAME_NAMES.get(ftype, ftype)} frame of "
            f"{len(payload)} bytes exceeds the {max_frame}-byte bound")
    return _HEADER.pack(len(payload)) + payload


def encode_binary_frame(ftype: int, header: dict, blob: bytes,
                        max_frame: int = MAX_FRAME_BYTES) -> bytes:
    """One wire-ready BINARY frame: length prefix + type byte + 4-byte
    big-endian JSON-header length + compact JSON header + raw blob.
    The blob rides verbatim — no base64, no escaping — so pool rows
    arrive bit-identical; the same ``max_frame`` bound applies to the
    whole payload (a handoff bigger than the bound is refused on the
    sending side, degrading that request to local prefill)."""
    if ftype not in BINARY_FRAMES:
        raise ProtocolError(
            f"{FRAME_NAMES.get(ftype, ftype)} is not a binary frame "
            f"type")
    if BLOB_KEY in header:
        raise ProtocolError(
            f"binary frame header may not use the reserved "
            f"{BLOB_KEY!r} key")
    hdr = json.dumps(header, separators=(",", ":")).encode()
    payload_len = 1 + _HEADER.size + len(hdr) + len(blob)
    if payload_len > max_frame:
        raise ProtocolError(
            f"outgoing {FRAME_NAMES.get(ftype, ftype)} frame of "
            f"{payload_len} bytes exceeds the {max_frame}-byte bound")
    return (_HEADER.pack(payload_len) + bytes([ftype])
            + _HEADER.pack(len(hdr)) + hdr + blob)


def write_frame(fp, ftype: int, body: dict,
                max_frame: int = MAX_FRAME_BYTES) -> None:
    """Write one frame and flush (callers serialize writers with their
    own lock — frames from concurrent relay threads must not
    interleave mid-frame)."""
    fp.write(encode_frame(ftype, body, max_frame))
    fp.flush()


class FrameSender:
    """Locked frame writer shared by every sending thread on one side
    of the stream (reader loop, per-request relays, stats heartbeat —
    or the parent driver's submitters): ONE lock so concurrent frames
    never interleave mid-frame.  A dead peer (EPIPE, torn socket)
    flips ``gone`` and returns False instead of killing the calling
    thread; an OVERSIZED outgoing frame also returns False but does
    NOT poison the stream (nothing was written) — callers that can
    answer a client distinguish it by pre-encoding with
    ``encode_frame`` themselves."""

    def __init__(self, fp, max_frame: int = MAX_FRAME_BYTES):
        self._fp = fp
        self._max_frame = max_frame
        self._lock = threading.Lock()
        self.gone = False

    def send_frame(self, frame: bytes) -> bool:
        """Write one pre-encoded frame atomically."""
        with self._lock:
            if self.gone:
                return False
            try:
                self._fp.write(frame)
                self._fp.flush()
                return True
            except (OSError, ValueError):
                self.gone = True
                return False

    def send(self, ftype: int, body: dict) -> bool:
        try:
            frame = encode_frame(ftype, body, self._max_frame)
        except ProtocolError:
            return False
        return self.send_frame(frame)

    def send_binary(self, ftype: int, header: dict, blob: bytes) -> bool:
        """Binary-frame analog of ``send``: oversized payloads return
        False without poisoning the stream (nothing was written)."""
        try:
            frame = encode_binary_frame(ftype, header, blob,
                                        self._max_frame)
        except ProtocolError:
            return False
        return self.send_frame(frame)


def _read_exact(fp, n: int) -> bytes:
    """Exactly ``n`` bytes, or everything the stream had left (the
    caller distinguishes clean EOF from a mid-frame death)."""
    chunks = []
    got = 0
    while got < n:
        b = fp.read(n - got)
        if not b:
            break
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def read_frame(fp, max_frame: int = MAX_FRAME_BYTES
               ) -> Optional[Tuple[int, dict]]:
    """Read one complete frame: ``(type, body)``, or ``None`` on clean
    EOF (stream closed exactly on a frame boundary — the normal end of
    a drained worker).  Everything else raises ``ProtocolError``:

    - a length prefix beyond ``max_frame`` fails WITHOUT reading the
      body (the bounded-read contract — a corrupt prefix cannot make
      the reader allocate or wait for gigabytes);
    - EOF inside the header or the payload is a mid-frame death
      (SIGKILLed worker, torn pipe);
    - a payload that is not ``type byte + JSON object`` is garbage.
    """
    header = _read_exact(fp, _HEADER.size)
    if not header:
        return None
    if len(header) < _HEADER.size:
        raise ProtocolError(
            f"stream died mid-frame: {len(header)} of "
            f"{_HEADER.size} header bytes")
    (n,) = _HEADER.unpack(header)
    if n < 1:
        raise ProtocolError("empty frame (length prefix 0)")
    if n > max_frame:
        raise ProtocolError(
            f"oversized length prefix: {n} bytes exceeds the "
            f"{max_frame}-byte frame bound (refusing the read)")
    payload = _read_exact(fp, n)
    if len(payload) < n:
        raise ProtocolError(
            f"stream died mid-frame: {len(payload)} of {n} "
            f"payload bytes")
    ftype = payload[0]
    if ftype in BINARY_FRAMES:
        # type byte + 4-byte header length + JSON header + raw blob;
        # the blob is delivered under the reserved "blob" body key.
        if len(payload) < 1 + _HEADER.size:
            raise ProtocolError(
                f"binary frame too short for its header length "
                f"({len(payload)} bytes)")
        (hn,) = _HEADER.unpack(payload[1:1 + _HEADER.size])
        hdr_end = 1 + _HEADER.size + hn
        if hdr_end > len(payload):
            raise ProtocolError(
                f"binary frame header length {hn} exceeds the "
                f"{len(payload)}-byte payload")
        try:
            body = json.loads(payload[1 + _HEADER.size:hdr_end].decode())
        except (UnicodeDecodeError, ValueError) as e:
            raise ProtocolError(
                f"binary frame header is not JSON "
                f"(type byte {ftype}): {e}") from None
        if not isinstance(body, dict):
            raise ProtocolError(
                f"binary frame header must be a JSON object, got "
                f"{type(body).__name__}")
        body[BLOB_KEY] = payload[hdr_end:]
        return ftype, body
    try:
        body = json.loads(payload[1:].decode())
    except (UnicodeDecodeError, ValueError) as e:
        raise ProtocolError(
            f"frame payload is not JSON "
            f"(type byte {ftype}): {e}") from None
    if not isinstance(body, dict):
        raise ProtocolError(
            f"frame body must be a JSON object, got "
            f"{type(body).__name__}")
    return ftype, body


def check_hello(ftype: int, body: dict) -> dict:
    """Validate the handshake frame; returns the body.  The FIRST
    frame must be a current-version HELLO — anything else means the
    two sides do not speak the same protocol and every later frame
    would be misparsed."""
    if ftype != HELLO:
        raise ProtocolError(
            f"expected HELLO as the first frame, got "
            f"{FRAME_NAMES.get(ftype, ftype)}")
    got = body.get("proto")
    if got != PROTO_VERSION:
        raise ProtocolError(
            f"protocol version mismatch: worker speaks {got!r}, "
            f"parent speaks {PROTO_VERSION}")
    return body
