"""Replica pool: N engine drivers behind one admission layer.

The gateway's single point of failure was its one ``EngineDriver`` — a
dead or hung driver turned every in-flight and queued request into a
loss.  This module fronts N engine replicas (in-process driver threads;
the seam deliberately admits subprocess replicas later — every
replica interaction goes through the ``EngineDriver`` surface, which an
IPC proxy can implement) with:

- **routing**: admissions go to the alive replica with the warmest
  KV affinity (a request whose prompt shares its leading KV block with
  one recently routed to a replica prefers that replica — its radix
  prefix cache holds the warm blocks), ties broken by load (waiting +
  active lanes), then index;
- **health**: per-replica ``driver.alive()`` plus a hung-dispatch
  watchdog — a decode chunk that exceeds ``watchdog_timeout_s``
  declares the replica dead even though its thread still exists (the
  wedged-device failure mode liveness alone cannot see).  A first
  dispatch COMPILES (XLA): size the watchdog above worst-case compile
  time, or warm every replica up before taking traffic (the
  bench/chaos harness idiom);
- **deterministic failover**: a request whose replica dies is
  re-admitted on a survivor with its ORIGINAL seed, its original
  prompt plus every token already committed, and
  ``resume_from=<committed count>`` — the engine's resume-from-token
  admission continues the request's rng stream at its original
  position, so greedy and seeded-sampling outputs equal an
  uninterrupted single-replica run, with no token duplicated or
  dropped (the stream simply continues);
- **bounded retry with backoff**: a placement refused for transient
  pool pressure (every replica's admission queue full) retries with
  exponential backoff and gives up at the request's own deadline
  instead of failing fast;
- **graceful drain**: replicas drain ONE AT A TIME, so capacity
  degrades gradually instead of all at once — and with ≥2 usable
  replicas a draining replica's live lanes are EVACUATED first;
- **live migration**: ``migrate(request_id, target=None)`` moves an
  ACTIVE stream between healthy replicas mid-generation — the lane's
  KV blocks, token history, rng position, and staged-prefill cursor
  cross via ``export_lane``/``install_lane`` and the stream resumes
  bitwise-identical, no re-prefill (``TTD_NO_MIGRATION=1`` disables);
  ``defragment()`` packs long-tail lanes onto fewer replicas.

Each pool request runs a small pump thread that places the request,
relays committed chunks from the replica's stream to the caller's
handle, and re-places on a survivor when the replica dies — the
caller-facing ``RequestHandle`` surface (``result()`` /
``iter_tokens()``) is exactly the single-driver one, so the gateway's
HTTP frontend is replica-blind.

Chaos: ``runtime.faults`` serve-side entries
(``serve:dispatch:N:raise|hang|kill9[:replica=K]``) kill exactly the
failure modes above — error-propagating death, hung dispatch, and
abrupt vanish — deterministically, per replica.
"""

from __future__ import annotations

import logging
import os
import queue as queue_mod
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    concurrency_guarded,
    thread_role,
)
from tensorflow_train_distributed_tpu.server.driver import (
    _DONE,
    _TERMINAL_KEEP,
    AdmissionFull,
    DeadlineExceeded,
    Draining,
    EngineDriver,
    RequestError,
    RequestHandle,
)

logger = logging.getLogger(__name__)


class NoReplicas(RuntimeError):
    """No live replica can accept work (HTTP 503 + Retry-After: the
    condition may clear — operators restart replicas — unlike a single
    driver's terminal death)."""


def disagg_killed() -> bool:
    """``TTD_NO_DISAGG=1`` disables disaggregated serving's role split
    and prefill→decode KV handoff: every worker is routed as
    ``role=both`` and requests prefill locally on whatever replica
    decodes them (the pre-disagg behavior, bitwise-identical outputs —
    handoff only ever changes WHERE prefill runs).  The TCP transport
    itself stays up: killing routing must not take a cross-host fleet
    offline.  Same no-redeploy contract as ``TTD_NO_PROC_REPLICAS``."""
    return os.environ.get("TTD_NO_DISAGG", "0") not in ("", "0")


def migration_killed() -> bool:
    """``TTD_NO_MIGRATION=1`` disables live mid-stream migration:
    ``ReplicaPool.migrate`` refuses, drain-time evacuation and the
    elastic scaler's pack-drain revert to the pre-migration behavior
    byte-for-byte (drains wait for accepted work to finish; deaths
    fail over via resume-from-token re-prefill), and ``defragment``
    is a no-op.  The ``MIGRATE`` protocol frames stay registered —
    killing the feature must never change what the transport can
    parse.  Same no-redeploy contract as ``TTD_NO_DISAGG``."""
    return os.environ.get("TTD_NO_MIGRATION", "0") not in ("", "0")


# Pump liveness poll while waiting on the next chunk: only paid when
# the stream is IDLE (a ready chunk returns immediately), so it bounds
# failover detection latency, not token latency.
_POLL_S = 0.05

# Recent first-block routing keys remembered per replica (the affinity
# table's LRU bound).
_AFFINITY_KEEP = 512


@concurrency_guarded
class Replica:
    """One engine + its driver + the pool-level health state."""

    # The affinity LRU is read by handler threads (routing scans) while
    # pump threads note placements — every touch locks.  The health
    # pair is ATOMIC-PUBLISH: written exactly once, by the watchdog
    # monitor alone (``mark_dead``), read lock-free everywhere —
    # single-field reads are safe, and the write ORDER (reason first,
    # flag second) guarantees a reader that saw ``dead`` also sees why.
    _GUARDED_BY = {
        "_affinity": ("_aff_lock",),
        "dead": (None, "watchdog"),
        "dead_reason": (None, "watchdog"),
    }

    def __init__(self, idx: int, engine, *, max_queue: int,
                 default_timeout_s: Optional[float],
                 retry_after_s: float, driver=None):
        self.idx = idx
        self.engine = engine
        # ``driver`` injection is the subprocess seam: a ProcDriver
        # (server.procpool) implements the same surface over the frame
        # protocol, and everything else in this module — routing,
        # health, failover, drain — consumes it unchanged.
        if driver is None:
            # validate=None: the pool screens once at its own admission.
            driver = EngineDriver(
                engine, max_queue=max_queue, validate=None,
                default_timeout_s=default_timeout_s,
                retry_after_s=retry_after_s, replica_id=idx)
        self.driver = driver
        self.dead = False
        self.dead_reason: Optional[str] = None
        self._affinity: OrderedDict = OrderedDict()   # block key -> None
        self._aff_lock = threading.Lock()

    @property
    def slots(self) -> int:
        """Live read: a subprocess replica's facade learns its slot
        count at the HELLO handshake, after construction."""
        return getattr(self.engine, "slots", 0)

    def state(self) -> str:
        if self.dead:
            return "dead"
        if self.driver.is_draining():
            # "drained": an orderly drain that already finished (the
            # elastic pool's scale-down end state) — distinct from a
            # drain in progress, which still finishes accepted work,
            # and from a worker that VANISHED mid-drain (SIGKILL/OOM
            # before its BYE): that one is a death the monitor is
            # about to classify, and the scaler must never prune it
            # as a clean scale-down.
            if self.driver.alive():
                return "draining"
            return "dead" if self.driver.vanished() else "drained"
        return "alive"

    def accepting(self) -> bool:
        """Routable for NEW admissions (drain/death excluded)."""
        return (not self.dead and self.driver.alive()
                and not self.driver.is_draining())

    def usable(self) -> bool:
        """Usable for failover/drain-time re-admission: a DRAINING
        replica still finishes accepted work, and a failed-over request
        was accepted once — only death disqualifies."""
        return not self.dead and self.driver.alive()

    def role(self) -> str:
        """Disaggregated-serving role (``prefill|decode|both``) from
        the worker's HELLO; in-process engines have none and serve
        everything.  Under ``TTD_NO_DISAGG=1`` every replica reads as
        ``both`` — the kill switch collapses routing, not health."""
        if disagg_killed():
            return "both"
        role = getattr(self.engine, "role", None) or "both"
        return role if role in ("prefill", "decode", "both") else "both"

    def decode_capable(self) -> bool:
        """May this replica take a decode placement?  Dedicated
        prefill workers only stage and export KV — they are never
        placement candidates."""
        return self.role() != "prefill"

    def load(self) -> int:
        return self.driver.waiting() + self.driver.active_slots()

    @thread_role("watchdog")
    def mark_dead(self, reason: str) -> None:
        """Publish the death verdict (monitor thread only).  The
        REASON is written before the flag: readers everywhere check
        ``dead`` first and then format ``dead_reason`` into errors and
        health bodies lock-free, so the old flag-first order could
        publish a death with a ``None`` explanation mid-read."""
        self.dead_reason = reason
        self.dead = True

    def note_affinity(self, key) -> None:
        if key is None:
            return
        with self._aff_lock:
            self._affinity[key] = None
            self._affinity.move_to_end(key)
            while len(self._affinity) > _AFFINITY_KEEP:
                self._affinity.popitem(last=False)

    def affinity(self, key) -> int:
        if key is None:
            return 0
        with self._aff_lock:
            return 1 if key in self._affinity else 0


class _PoolRequest:
    """Pool-side record of one live request (the pump's state)."""

    __slots__ = ("handle", "generated", "replica", "inner", "excluded",
                 "failovers", "affinity_key", "thread",
                 "queue_wait_seen", "preferred", "avoid",
                 "migrate_to", "migrate_done", "migrate_ok",
                 "migrations")

    def __init__(self, handle: RequestHandle, affinity_key):
        self.handle = handle
        self.generated: list = []      # committed tokens relayed so far
        self.replica: Optional[Replica] = None
        self.inner: Optional[RequestHandle] = None
        self.excluded: set = set()     # replica idxs that died under it
        self.failovers = 0
        self.affinity_key = affinity_key
        self.thread: Optional[threading.Thread] = None
        self.queue_wait_seen = False
        # Migration steering — SOFT, unlike ``excluded``: ``preferred``
        # sorts first at the next placement (the migration target,
        # where the KV just landed) and ``avoid`` is pruned only while
        # alternatives remain (the evacuating source stays a legal
        # last resort — it is alive, unlike a death-excluded replica).
        self.preferred: Optional[int] = None
        self.avoid: Optional[int] = None
        # Migration rendezvous: ``migrate()`` publishes a target
        # (Replica | "auto") and waits on the event; the pump's relay
        # loop — the single consumer of the inner stream — performs
        # the move inline and signals back.
        self.migrate_to = None
        self.migrate_done: Optional[threading.Event] = None
        self.migrate_ok = False
        self.migrations = 0


@concurrency_guarded
class ReplicaPool:
    """N replicas behind the ``EngineDriver`` submission surface.

    The gateway talks to this exactly as it talks to a single driver
    (``submit``/``waiting``/``active_slots``/``alive``/``drain``/
    ``join``/``request_status``/``abandon``), so the HTTP layer is
    replica-blind; everything replica-aware (routing, health, failover,
    per-replica drain) lives here.
    """

    # Touched by handler threads (submit/status), pump threads
    # (_finish), and the drain path — every access locks (``_lock`` is
    # re-entrant, so submit's nested waiting()/alive() reads are fine).
    # ``_replicas`` is ATOMIC-PUBLISH: the list object is immutable
    # once published (the elastic proc pool's scaler REPLACES it with
    # a new list on spawn/prune, never mutates it in place), so every
    # reader iterates a consistent snapshot lock-free.
    _GUARDED_BY = {
        "_requests": ("_lock",),
        "_terminal": ("_lock",),
        "_draining": ("_lock",),
        "_next_id": ("_lock",),
        "_replicas": (None, "scaler", "main"),
    }

    def __init__(self, engines, *, max_queue: int = 64,
                 validate: Optional[Callable] = None,
                 default_timeout_s: Optional[float] = None,
                 retry_after_s: float = 1.0,
                 watchdog_timeout_s: Optional[float] = 30.0,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 1.0,
                 replica_max_queue: Optional[int] = None,
                 monitor_poll_s: Optional[float] = None):
        engines = list(engines)
        # The network pool starts EMPTY (its replicas dial in) and
        # opts out of this floor; every other pool needs a replica up
        # front.
        if len(engines) < 1 and not getattr(self, "_allow_empty",
                                            False):
            raise ValueError("ReplicaPool needs at least one engine")
        if watchdog_timeout_s is not None and watchdog_timeout_s <= 0:
            raise ValueError(
                f"watchdog_timeout_s must be > 0 (None disables), got "
                f"{watchdog_timeout_s}")
        self._validate = validate
        self._max_queue = max_queue
        self._default_timeout_s = default_timeout_s
        self._retry_after_s = retry_after_s
        self._watchdog_s = watchdog_timeout_s
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        # Per-replica admission bound: the pool-wide ``max_queue`` is
        # the SHED bound (429); each replica's driver holds its share,
        # so a skewed placement (affinity pinning, uneven drain) hits a
        # TRANSIENT per-replica refusal the pump absorbs with backoff
        # instead of a client-visible shed.
        if replica_max_queue is None:
            replica_max_queue = max(
                1, -(-max_queue // max(1, len(engines))))
        self._replica_max_queue = replica_max_queue
        self._replicas = [self._make_replica(i, e)
                          for i, e in enumerate(engines)]
        self._metrics = None
        # RLock: submit() holds it across its waiting()/alive() checks
        # (which take it again) so admission decisions are atomic.
        self._lock = threading.RLock()
        self._requests: dict = {}          # pool id -> _PoolRequest
        self._terminal: OrderedDict = OrderedDict()
        self._next_id = 0
        self._draining = False
        self._stop = threading.Event()
        if monitor_poll_s is None:
            monitor_poll_s = (min(0.05, watchdog_timeout_s / 4)
                              if watchdog_timeout_s else 0.05)
        self._monitor_poll_s = monitor_poll_s
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="replica-monitor", daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def _make_replica(self, idx: int, engine) -> Replica:
        """Build one replica — the subclass seam: the subprocess pool
        builds a ProcDriver-backed replica from a worker SPEC here
        instead of an in-process engine."""
        return Replica(idx, engine, max_queue=self._replica_max_queue,
                       default_timeout_s=self._default_timeout_s,
                       retry_after_s=self._retry_after_s)

    def _placement_may_recover(self) -> bool:
        """May capacity come back without operator action?  The base
        pool's replicas never resurrect — an empty candidate set is
        terminal (``NoReplicas``).  The elastic subprocess pool
        overrides this while its respawn budget lasts, so a request
        caught between a death and the respawn WAITS (bounded by its
        own deadline) instead of failing."""
        return False

    def start(self) -> "ReplicaPool":
        for rep in self._replicas:
            rep.driver.start()
        self._monitor_thread.start()
        return self

    def set_metrics(self, metrics) -> None:
        self._metrics = metrics

    @property
    def replicas(self) -> list:
        return self._replicas

    # -- health / occupancy ------------------------------------------------

    def alive(self) -> bool:
        """True while at least one replica can make progress."""
        return any(rep.usable() for rep in self._replicas)

    def alive_count(self) -> int:
        return sum(rep.usable() for rep in self._replicas)

    def degraded(self) -> bool:
        """Is serving capacity reduced?  For the base pool any dead
        replica is capacity gone for good (replicas never resurrect).
        The elastic subprocess pool overrides this: a respawned fleet
        back at strength is NOT degraded even though its corpses stay
        listed for forensics — /healthz keys the load-balancer signal
        here, not on corpse counting."""
        return self.alive_count() < len(self._replicas)

    def failure(self) -> Optional[BaseException]:
        """Total-loss summary once EVERY replica is dead, else None
        (one dead replica is a degraded pool, not a failed one)."""
        if self.alive():
            return None
        reasons = [f"replica {rep.idx}: {rep.dead_reason or 'dead'}"
                   for rep in self._replicas]
        return RuntimeError("all replicas dead (" + "; ".join(reasons)
                            + ")")

    def waiting(self) -> int:
        """Requests admitted by the pool but not yet decoding anywhere:
        un-placed pump requests plus the live replicas' own queues."""
        with self._lock:
            unplaced = sum(1 for preq in self._requests.values()
                           if preq.inner is None)
        return unplaced + sum(rep.driver.waiting()
                              for rep in self._replicas if rep.usable())

    def active_slots(self) -> int:
        return sum(rep.driver.active_slots()
                   for rep in self._replicas if rep.usable())

    def is_draining(self) -> bool:
        with self._lock:
            return self._draining

    def replica_states(self) -> list:
        """Per-replica health the /healthz endpoint reports."""
        out = []
        for rep in self._replicas:
            d = {"replica": rep.idx, "state": rep.state(),
                 "queue_depth": rep.driver.waiting(),
                 "slots_in_use": rep.driver.active_slots(),
                 "slots_total": rep.slots}
            role = rep.role()
            if role != "both":
                d["role"] = role
            if d["state"] == "draining":
                # The evacuation progress gauge: live pool requests
                # still homed on this draining replica.  Operators
                # watch it count down to 0 as lanes migrate off.
                with self._lock:
                    d["lanes_remaining"] = sum(
                        1 for preq in self._requests.values()
                        if preq.replica is rep)
            if rep.dead_reason:
                d["reason"] = rep.dead_reason
            total_fn = getattr(rep.engine, "kv_blocks_total", None)
            total = total_fn() if total_fn is not None else 0
            if total:
                d["kv_blocks_total"] = total
                d["kv_blocks_free"] = (total
                                       - rep.engine.kv_blocks_in_use())
                # Bytes next to blocks: the same capacity signal in
                # the unit budgets reason in (per replica — under
                # --replica-procs each worker reports its own pool
                # from its stats frames instead of dropping it).
                # kv_pool_bytes is the constant capacity,
                # kv_bytes_in_use the referenced-blocks occupancy.
                for name in ("kv_pool_bytes", "kv_bytes_in_use"):
                    fn = getattr(rep.engine, name, None)
                    v = fn() if fn is not None else 0
                    if v:
                        d[name] = v
            hbm_fn = getattr(rep.engine, "hbm_by_pool", None)
            if hbm_fn is not None:
                hbm = hbm_fn()
                if hbm:
                    d["hbm_bytes"] = hbm
            # Driver-specific extras: a subprocess replica's ProcDriver
            # reports pid/rss/protocol state here, so /healthz
            # classifies worker-level failures per replica.
            extra = getattr(rep.driver, "health_extra", None)
            if extra is not None:
                d.update(extra())
            out.append(d)
        return out

    # -- engine-stat aggregation (the gateway's /metrics feed) -------------

    def slots_total(self) -> int:
        """Current slot capacity across usable replicas — a LIVE value
        under the elastic subprocess pool (workers spawn and drain)."""
        return sum(rep.slots for rep in self._replicas if rep.usable())

    def workers_by_role(self) -> dict:
        """Usable replicas per disaggregated-serving role (``{role:
        count}``) — the ``ttd_gateway_workers_alive{role=...}`` feed.
        Under ``TTD_NO_DISAGG=1`` everything truthfully reads
        ``both``."""
        out: dict = {}
        for rep in self._replicas:
            if rep.usable():
                r = rep.role()
                out[r] = out.get(r, 0) + 1
        return out

    def _engine_stat(self, name: str, ratio: bool = False) -> float:
        vals = []
        for rep in self._replicas:
            if not rep.usable():
                continue
            fn = getattr(rep.engine, name, None)
            if fn is None:
                continue
            vals.append(float(fn()))
        if not vals:
            return 0.0
        return sum(vals) / len(vals) if ratio else sum(vals)

    def overlap_ratio(self) -> float:
        return self._engine_stat("overlap_ratio", ratio=True)

    def prefill_stall_s(self) -> float:
        return self._engine_stat("prefill_stall_s")

    def kv_blocks_in_use(self) -> float:
        return self._engine_stat("kv_blocks_in_use")

    def kv_blocks_total(self) -> float:
        return self._engine_stat("kv_blocks_total")

    def kv_prefix_hit_tokens(self) -> float:
        return self._engine_stat("kv_prefix_hit_tokens")

    def kv_evictions(self) -> float:
        return self._engine_stat("kv_evictions")

    def kv_pool_bytes(self) -> float:
        return self._engine_stat("kv_pool_bytes")

    def spec_depth(self) -> float:
        """Fleet draft depth (MEAN over usable replicas — they share
        one spec, so a non-integer read means the controllers have
        diverged on their own traffic, itself worth seeing)."""
        return self._engine_stat("spec_depth", ratio=True)

    def spec_accepted_tokens(self) -> float:
        return self._engine_stat("spec_accepted_tokens")

    def spec_drafted_tokens(self) -> float:
        return self._engine_stat("spec_drafted_tokens")

    def hbm_autosized_bytes(self) -> float:
        return self._engine_stat("hbm_autosized_bytes")

    def hbm_by_pool(self) -> dict:
        """Live bytes per declared memcheck pool, for the labeled
        ``ttd_engine_hbm_bytes{pool=...}`` gauge.  Subprocess replicas
        report their own ledgers through stats frames — rendered as
        ``<replica>/<pool>`` so fleet memory is visible PER WORKER;
        in-process replicas all live in this process, whose global
        ledger is the truth (summing per engine would double-count
        nothing, but the process view already covers every engine)."""
        out: dict = {}
        remote = False
        for rep in self._replicas:
            fn = getattr(rep.engine, "hbm_by_pool", None)
            if fn is None or not rep.usable():
                continue
            remote = True
            for pool, v in fn().items():
                out[f"{rep.idx}/{pool}"] = float(v)
        if not remote:
            from tensorflow_train_distributed_tpu.runtime.lint import (
                memcheck,
            )

            out = memcheck.live_by_pool()
        return out

    def programs_by_site(self) -> dict:
        """Fleet roofline numerators: each worker's relayed program
        stats keyed ``<replica>/<site>`` (subprocess/TCP facades), or
        this process's own compilecheck ledger for in-process replicas
        — same shape as ``hbm_by_pool``, consumed by
        ``mfu_by_program``/``mbu_by_program`` below."""
        out: dict = {}
        remote = False
        for rep in self._replicas:
            fn = getattr(rep.engine, "program_stats", None)
            if fn is None or not rep.usable():
                continue
            remote = True
            for site, stats in fn().items():
                out[f"{rep.idx}/{site}"] = dict(stats)
        if not remote:
            from tensorflow_train_distributed_tpu.runtime.lint import (
                compilecheck,
            )

            out = compilecheck.program_stats()
        return out

    def mfu_by_program(self) -> dict:
        """Fleet ``ttd_engine_mfu_pct`` source: every replica's
        achieved flop rate against THIS host's device peak (homogeneous
        fleets; heterogeneous ones pin TTD_PEAK_FLOPS).  Empty when the
        peak is unknown — no made-up percentages."""
        from tensorflow_train_distributed_tpu.runtime.lint import (
            compilecheck,
        )

        peak = compilecheck.peak_flops_per_s()
        if not peak:
            return {}
        return {prog: round(100.0 * float(s.get("flops_per_s", 0.0))
                            / peak, 3)
                for prog, s in self.programs_by_site().items()
                if s.get("dispatches")}

    def mbu_by_program(self) -> dict:
        """Fleet ``ttd_engine_mbu_pct`` source (see mfu_by_program)."""
        from tensorflow_train_distributed_tpu.runtime.lint import (
            compilecheck,
        )

        peak = compilecheck.peak_hbm_bytes_per_s()
        if not peak:
            return {}
        return {prog: round(100.0 * float(s.get("bytes_per_s", 0.0))
                            / peak, 3)
                for prog, s in self.programs_by_site().items()
                if s.get("dispatches")}

    def replica_rss(self) -> dict:
        """Per-replica resident-set bytes (``{replica: bytes}``) for
        engines that report it — subprocess facades do (from the stats
        frames); in-process replicas share the gateway's own rss and
        truthfully report nothing."""
        out = {}
        for rep in self._replicas:
            fn = getattr(rep.engine, "rss_bytes", None)
            if fn is None:
                continue
            v = fn()
            if v:
                out[str(rep.idx)] = float(v)
        return out

    # -- admission ---------------------------------------------------------

    def _affinity_key(self, prompt):
        """First-KV-block token key: requests sharing it share their
        leading physical blocks on whichever replica holds them."""
        reps = self._replicas
        bs = (getattr(reps[0].engine, "kv_block_size", 16) if reps
              else 16)
        return tuple(prompt[:bs]) if len(prompt) >= bs else None

    @thread_role("handler", "main")
    def submit(self, prompt, max_new: int, *, seed: Optional[int] = None,
               stream: bool = False,
               timeout_s: Optional[float] = None) -> RequestHandle:
        """Admit one request to the pool; raises ``RequestError``,
        ``AdmissionFull``, ``Draining``, or ``NoReplicas``.  The
        returned handle is the single-driver one — ``result()`` /
        ``iter_tokens()`` hide placement, retries, and failover."""
        if self._validate is not None:
            self._validate(prompt, max_new, seed)
        try:
            # The screening engine: any replica's validator agrees
            # (identically-configured engines); a subprocess pool's
            # facade answers from the HELLO-advertised shape.  An
            # EMPTY network pool (first worker still dialing in) can
            # only coerce — the worker's real engine screens at
            # placement, coming back as a classified invalid retire.
            reps = self._replicas
            if reps:
                prompt = reps[0].engine.validate_request(
                    prompt, max_new, seed)
            else:
                prompt = [int(t) for t in prompt]
                if not prompt:
                    raise ValueError("empty prompt")
        except ValueError as e:
            raise RequestError(str(e))
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        if timeout_s is not None and timeout_s <= 0:
            raise RequestError(f"timeout_s must be > 0, got {timeout_s}")
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        with self._lock:
            if self._draining:
                raise Draining("gateway is draining; not admitting")
            if not self.alive() and not self._placement_may_recover():
                raise NoReplicas(
                    "no live replica can accept work: "
                    + "; ".join(f"replica {r.idx} {r.state()}"
                                f" ({r.dead_reason})" if r.dead_reason
                                else f"replica {r.idx} {r.state()}"
                                for r in self._replicas))
            if self.waiting() >= self._max_queue:
                raise AdmissionFull(self.waiting(), self._retry_after_s)
            pool_id = self._next_id
            self._next_id += 1
            if seed is None:
                # Pin the effective seed NOW: an engine defaults a None
                # seed to its own internal rid, which (a) collides
                # across replicas — two engines both mint rid 0, so two
                # concurrent seedless sampled requests draw the SAME
                # default stream, breaking the distinct-per-request
                # contract — and (b) changes across a failover
                # re-admission, splicing an unrelated stream onto the
                # committed prefix.  The pool-unique id restores both;
                # greedy decode ignores it entirely.
                seed = pool_id % 2 ** 32
            handle = RequestHandle(pool_id, prompt, max_new, seed,
                                   stream, deadline)
            preq = _PoolRequest(handle, self._affinity_key(prompt))
            self._requests[pool_id] = preq
            # The pool-level admission anchor request_timeline keys on:
            # failover re-admits the SAME id on a survivor, and the
            # timeline must show every life plus the hop.
            events.instant("request/pool_admitted", request_id=pool_id,
                           prompt_len=len(prompt), max_new=max_new,
                           stream=stream)
        preq.thread = threading.Thread(
            target=self._pump, args=(preq,),
            name=f"pool-req-{pool_id}", daemon=True)
        preq.thread.start()
        return handle

    # -- placement ---------------------------------------------------------

    def _candidates(self, preq: _PoolRequest,
                    allow_draining: bool) -> list:
        """Routable DECODE-capable replicas, best first: warm KV
        affinity, then load, then index.  The affinity table is the
        gateway-side mirror of each worker's radix prefix index —
        placements AND finished handoffs feed it — so a warm prefix on
        ANY decode worker wins placement fleet-wide.  Dedicated
        prefill workers never take placements; a replica this request
        already died on is never a candidate (replicas do not
        resurrect)."""
        reps = [rep for rep in self._replicas
                if rep.idx not in preq.excluded
                and rep.decode_capable()
                and (rep.usable() if allow_draining
                     else rep.accepting())]
        if preq.avoid is not None:
            pruned = [r for r in reps if r.idx != preq.avoid]
            if pruned:
                reps = pruned       # soft: only while alternatives live
        key = preq.affinity_key
        reps.sort(key=lambda r: (r.idx != preq.preferred,
                                 -r.affinity(key), r.load(), r.idx))
        return reps

    def _place(self, preq: _PoolRequest, requeue: bool) -> None:
        """Submit the request (or its resumed remainder) to the best
        replica that will take it; when EVERY candidate refuses for
        transient pool pressure, retry with exponential backoff until
        the request's own deadline.  Raises ``DeadlineExceeded`` /
        ``NoReplicas`` when placement cannot happen."""
        outer = preq.handle
        backoff = self._backoff_base_s
        allow_draining = requeue or self.is_draining()
        while True:
            if (outer.deadline is not None
                    and time.monotonic() >= outer.deadline):
                raise DeadlineExceeded(
                    f"request {outer.id} exceeded its deadline")
            # Re-read the drain flag every pass: a pump looping in the
            # backoff branch when drain BEGINS must widen its candidate
            # set to draining replicas (accepted work runs to
            # completion), not starve into NoReplicas.
            allow_draining = allow_draining or self.is_draining()
            reps = self._candidates(preq, allow_draining)
            if not reps and not self._placement_may_recover():
                raise NoReplicas(
                    f"request {outer.id}: no live replica left "
                    f"(excluded: {sorted(preq.excluded)})")
            gen = len(preq.generated)
            prompt = (outer.prompt + preq.generated if gen
                      else outer.prompt)
            timeout_s = None
            if outer.deadline is not None:
                timeout_s = max(1e-3,
                                outer.deadline - time.monotonic())
            # Empty candidate set with recovery possible (the elastic
            # pool's respawn window): wait out the backoff exactly
            # like an everyone-refused pass — capacity is coming.
            refused = not reps
            for rep in reps:
                self._maybe_handoff(preq, prompt, rep)
                try:
                    inner = rep.driver.submit(
                        prompt, outer.max_new - gen, seed=outer.seed,
                        stream=True, timeout_s=timeout_s,
                        request_id=outer.id, resume_from=gen,
                        requeue=requeue or allow_draining)
                except AdmissionFull:
                    refused = True
                    continue
                except Draining:
                    # Began draining between the candidate scan and
                    # the submit: the next pass re-scans with the
                    # drain-aware rule.
                    allow_draining = True
                    continue
                except RuntimeError:
                    # Driver died between scan and submit; the monitor
                    # will mark it — never a candidate again.
                    preq.excluded.add(rep.idx)
                    continue
                rep.note_affinity(preq.affinity_key)
                preq.replica, preq.inner = rep, inner
                return
            if not refused:
                continue        # candidate set changed under us: rescan
            # EVERY candidate refused (transient pool pressure): the
            # wait is bounded by the request's own deadline, so backoff
            # replaces fail-fast INSIDE the pool — the pool-level bound
            # in submit() still sheds 429 when the whole pool is over
            # capacity.
            if self._metrics is not None:
                self._metrics.retries.inc()
            events.instant("request/place_retry", request_id=outer.id,
                           backoff_s=round(backoff, 4))
            sleep = backoff
            if outer.deadline is not None:
                sleep = min(sleep, max(
                    0.0, outer.deadline - time.monotonic()))
            time.sleep(sleep)
            backoff = min(backoff * 2, self._backoff_cap_s)

    # -- prefill→decode KV handoff (disaggregated serving) -----------------

    def _prefill_workers(self) -> list:
        """Usable DEDICATED prefill replicas whose driver speaks the
        handoff exchange, least loaded first."""
        pres = [rep for rep in self._replicas
                if rep.usable() and rep.role() == "prefill"
                and getattr(rep.driver, "prefill_export", None)
                is not None]
        pres.sort(key=lambda r: (r.load(), r.idx))
        return pres

    def _maybe_handoff(self, preq: _PoolRequest, prompt,
                       rep: Replica) -> None:
        """Stage the prompt's head on a dedicated prefill worker and
        install the exported KV rows on the chosen decode replica
        BEFORE submitting — admission then takes the radix prefix hit,
        which is already pinned bitwise-identical to a local prefill,
        so disaggregation never changes output, only where prefill
        runs.  Every failure path (no prefill worker, export refusal,
        oversized frame, install refusal, a worker dying mid-handoff)
        silently degrades the request to a local prefill; a prefill
        worker that dies mid-export simply loses its staged work and
        the request re-enters here on the next prefill candidate —
        nothing was committed anywhere."""
        if disagg_killed():
            return
        install = getattr(rep.driver, "install_handoff", None)
        if install is None:
            return
        bs = getattr(rep.engine, "kv_block_size", 16) or 16
        if len(prompt) <= bs:
            return          # nothing exportable (the engine keeps at
            #                 least one suffix token for decode anyway)
        if rep.affinity(preq.affinity_key):
            return          # already warm there: placement wins as-is
        t0 = time.monotonic()
        for pre in self._prefill_workers():
            # Spans, not just the terminal instant: the fleet waterfall
            # (tools/trace_report.py --fleet) reads the export span's
            # end → install span's start gap as the handoff's measured
            # wire+queue hop, in the parent's own clock domain (no
            # offset correction involved, so the hop is positive by
            # construction and comparable across skewed workers).
            with events.span("handoff/export",
                             request_id=preq.handle.id,
                             prefill_replica=pre.idx):
                try:
                    out = pre.driver.prefill_export(prompt)
                except RuntimeError:
                    out = None  # prefill worker died between scan/ask
            if out is None:
                continue    # refusal (or death mid-export): next one
            meta, blob = out
            with events.span("handoff/install",
                             request_id=preq.handle.id,
                             decode_replica=rep.idx,
                             bytes=len(blob)):
                try:
                    n = install(meta, blob)
                except RuntimeError:
                    n = 0
            if n:
                rep.note_affinity(preq.affinity_key)
                m = self._metrics
                if m is not None:
                    hb = getattr(m, "handoff_bytes", None)
                    if hb is not None:
                        hb.inc(len(blob))
                    hs = getattr(m, "handoff_seconds", None)
                    if hs is not None:
                        hs.observe(time.monotonic() - t0)
                events.instant("request/kv_handoff",
                               request_id=preq.handle.id,
                               prefill_replica=pre.idx,
                               decode_replica=rep.idx,
                               tokens=int(n), bytes=len(blob))
            # Decode-side refusal is final for this placement (its
            # engine said no — e.g. pool pressure); local prefill.
            return

    # -- the per-request pump ----------------------------------------------

    @thread_role("pump")
    def _pump(self, preq: _PoolRequest) -> None:
        outer = preq.handle
        requeue = False
        try:
            while True:
                try:
                    self._place(preq, requeue)
                except DeadlineExceeded as e:
                    self._finish(preq, None, e, "expired")
                    return
                except NoReplicas as e:
                    self._finish(preq, None, e, "error")
                    return
                except RequestError as e:
                    self._finish(preq, None, e, "invalid")
                    return
                verdict = self._relay(preq)
                if verdict == "done":
                    self._finish(preq,
                                 list(outer.prompt) + preq.generated,
                                 None, "ok")
                    return
                if verdict in ("failover", "migrate"):
                    # Both re-place from the last committed token with
                    # resume-from-token determinism; migration differs
                    # only in that the KV already landed on the target
                    # (radix hit instead of re-prefill) and the source
                    # is avoided, not excluded.
                    requeue = True
                    continue
                return                      # _relay already finished it
        except BaseException as e:          # noqa: BLE001 — fail loudly
            logger.exception("pool pump for request %d died", outer.id)
            self._finish(preq, None,
                         RuntimeError(f"pool pump failed: {e!r}"),
                         "error")

    def _relay(self, preq: _PoolRequest) -> str:
        """Relay committed chunks from the inner stream to the outer
        handle until the life ends: returns ``"done"``, ``"failover"``
        (replica died — the pump re-places), ``"migrate"`` (the lane
        was exported off this replica — the pump re-places onto the
        target), or ``"finished"`` when a terminal error was already
        delivered."""
        outer, inner, rep = preq.handle, preq.inner, preq.replica
        q = inner._queue
        while True:
            if preq.migrate_to is not None:
                # A migration was requested (operator move, drain
                # evacuation, defrag).  The relay thread is the single
                # consumer of the inner stream, so running the move
                # HERE means no chunk can be relayed mid-export.
                verdict = self._migrate_now(preq)
                if verdict is not None:
                    return verdict
            try:
                item = q.get(timeout=_POLL_S)
            except queue_mod.Empty:
                if rep.dead or not rep.driver.alive():
                    # The monitor declared the replica dead (hung
                    # dispatch or vanish) — or its driver thread is
                    # simply gone (a drain race can strand a late
                    # requeue): either way the inner handle will never
                    # resolve; fail over from the last COMMITTED token.
                    # (A normally-drained request delivers its _DONE
                    # before the thread exits, so reaching here with a
                    # dead thread means the handle truly dangles.)
                    return self._begin_failover(
                        preq, rep.dead_reason or "replica gone")
                continue
            if item is _DONE:
                return "done"
            if isinstance(item, DeadlineExceeded):
                self._finish(preq, None, item, "expired")
                return "finished"
            if isinstance(item, RequestError):
                self._finish(preq, None, item, "invalid")
                return "finished"
            if isinstance(item, BaseException):
                # The driver loop died with error propagation: the
                # replica is (about to be marked) dead; fail over.
                return self._begin_failover(preq, repr(item))
            # A committed chunk of generated tokens.
            preq.generated.extend(item)
            self._on_chunk(preq, item)

    def _begin_failover(self, preq: _PoolRequest, reason: str) -> str:
        rep = preq.replica
        preq.excluded.add(rep.idx)
        preq.failovers += 1
        preq.replica = preq.inner = None
        if self._metrics is not None:
            self._metrics.failovers.inc()
        events.instant("request/failover", request_id=preq.handle.id,
                       from_replica=rep.idx,
                       resumed_at=len(preq.generated),
                       reason=str(reason)[:200])
        logger.warning(
            "request %d failing over from replica %d at %d generated "
            "tokens (%s)", preq.handle.id, rep.idx,
            len(preq.generated), reason)
        return "failover"

    # -- live mid-stream migration -----------------------------------------

    @thread_role("handler", "main", "scaler", "watchdog")
    def migrate(self, request_id: int, target: Optional[int] = None,
                timeout_s: float = 30.0) -> bool:
        """Move one live request to another replica mid-stream WITHOUT
        losing its KV: export the lane (block-table rows + token
        history + rng counter, the KV_HANDOFF byte recipe), install it
        on the target, and re-place the request there — it resumes
        decoding bitwise (resume-from-token pins the rng stream; the
        radix hit on the shipped rows replaces the re-prefill failover
        would pay).  ``target`` picks a replica index; None lets the
        pool choose (warmest affinity, then load).  Returns True once
        the move committed, False when it could not happen (unknown or
        finished request, no usable target, export refusal, the
        ``TTD_NO_MIGRATION`` kill switch) — the request keeps running
        where it was in every False case EXCEPT an export that
        committed on the source and then failed to land: that one
        still completes via the normal failover re-placement, tokens
        intact (the no-token-lost contract is placement-independent).

        Blocks up to ``timeout_s`` for the pump to perform the move
        (the relay thread owns the inner stream; migration runs there
        so no chunk can race the export)."""
        if migration_killed():
            return False
        with self._lock:
            preq = self._requests.get(request_id)
        if preq is None:
            return False
        want = "auto"
        if target is not None:
            want = next((r for r in self._replicas
                         if r.idx == int(target)), None)
            if (want is None or not want.usable()
                    or not want.decode_capable()):
                return False
        done = threading.Event()
        preq.migrate_ok = False
        preq.migrate_done = done
        preq.migrate_to = want      # published last: the relay's cue
        if not done.wait(timeout_s):
            return False
        return bool(preq.migrate_ok)

    def _migrate_now(self, preq: _PoolRequest) -> Optional[str]:
        """Perform a requested migration on the relay thread; returns
        ``"migrate"`` when the lane left the source (the pump must
        re-place), None when the move could not happen and the relay
        should keep streaming from the current replica."""
        outer, src = preq.handle, preq.replica
        want = preq.migrate_to
        target = want if isinstance(want, Replica) else None
        if target is None:
            cands = [r for r in self._replicas
                     if r is not src and r.usable()
                     and r.decode_capable()
                     and r.idx not in preq.excluded]
            key = preq.affinity_key
            cands.sort(key=lambda r: (-r.affinity(key), r.load(),
                                      r.idx))
            target = cands[0] if cands else None
        ok, warm, blob_len = False, 0, 0
        t0 = time.monotonic()
        if (target is not None and target is not src
                and src is not None and target.usable()
                and not migration_killed()):
            export = getattr(src.driver, "export_lane", None)
            out = None
            if export is not None:
                try:
                    # Bounded: a replica that VANISHES mid-export
                    # (kill9 semantics — pending calls never resolve)
                    # must not wedge the relay thread forever; the
                    # timeout lands in the except arm and the stream
                    # finishes via the normal failover re-placement.
                    out = export(outer.id, timeout_s=30.0)
                except (RuntimeError, TimeoutError) as e:
                    # Source died or wedged mid-export: nothing moved
                    # (or the reply was lost AFTER the source retired
                    # the lane — then the inner handle errors out and
                    # the normal failover path resumes from the last
                    # committed token; either way no token is lost).
                    logger.warning(
                        "request %d: migration export from replica %d "
                        "failed (%s)", outer.id, src.idx, e)
            if out is not None:
                meta, blob = out
                blob_len = len(blob)
                # The source retired the lane at export — from here
                # the move MUST complete via re-placement.  The meta
                # token history is authoritative (snapshotted between
                # engine steps, always >= what the relay delivered):
                # commit the tail the stream never saw.
                toks = meta.get("tokens")
                if toks:
                    base = len(outer.prompt) + len(preq.generated)
                    fresh = [int(t) for t in toks[base:]]
                    if fresh:
                        preq.generated.extend(fresh)
                        self._on_chunk(preq, fresh)
                install = getattr(target.driver, "install_lane", None)
                if install is not None and blob:
                    try:
                        warm = int(install(meta, blob,
                                           timeout_s=30.0) or 0)
                    except (RuntimeError, TimeoutError,
                            ValueError) as e:
                        # Install refusal/death is benign: the
                        # re-placed request prefills locally —
                        # exactly the failover path, bitwise.
                        logger.warning(
                            "request %d: migration install on replica "
                            "%d refused (%s)", outer.id, target.idx, e)
                        warm = 0
                target.note_affinity(preq.affinity_key)
                preq.preferred, preq.avoid = target.idx, src.idx
                preq.replica = preq.inner = None
                preq.migrations += 1
                dt = time.monotonic() - t0
                m = self._metrics
                if m is not None:
                    c = getattr(m, "migrations", None)
                    if c is not None:
                        c.inc()
                    h = getattr(m, "migration_seconds", None)
                    if h is not None:
                        h.observe(dt)
                    b = getattr(m, "migrated_kv_bytes", None)
                    if b is not None:
                        b.inc(blob_len)
                events.instant("request/migrate", request_id=outer.id,
                               from_replica=src.idx,
                               to_replica=target.idx,
                               tokens=int(warm), bytes=blob_len,
                               resumed_at=len(preq.generated),
                               ms=round(dt * 1e3, 3))
                logger.info(
                    "request %d migrated replica %d -> %d at %d "
                    "generated tokens (%d warm, %d bytes)", outer.id,
                    src.idx, target.idx, len(preq.generated), warm,
                    blob_len)
                ok = True
        preq.migrate_ok = ok
        preq.migrate_to = None
        ev, preq.migrate_done = preq.migrate_done, None
        if ev is not None:
            ev.set()
        return "migrate" if ok else None

    def _evacuate(self, rep: Replica,
                  timeout: Optional[float] = None) -> int:
        """Migrate every live request off ``rep`` (drain-time
        evacuation): with >=2 usable replicas a drain no longer makes
        its streams WAIT for natural completion — they move and keep
        decoding elsewhere.  Returns the number of requests moved;
        whatever could not move (no survivor, export refusal, the
        kill switch) simply drains the old way."""
        if migration_killed():
            return 0
        if not any(r is not rep and r.usable() and r.decode_capable()
                   for r in self._replicas):
            return 0
        with self._lock:
            victims = [preq.handle.id
                       for preq in self._requests.values()
                       if preq.replica is rep]
        if not victims:
            return 0
        per = 30.0 if timeout is None else max(1e-3,
                                               min(30.0, timeout))
        moved = sum(self.migrate(rid, timeout_s=per)
                    for rid in victims)
        events.instant("replica/evacuate", replica=rep.idx,
                       lanes=len(victims), moved=moved)
        logger.info("replica %d evacuated: %d/%d lanes migrated",
                    rep.idx, moved, len(victims))
        return moved

    @thread_role("handler", "main", "scaler")
    def defragment(self, max_moves: int = 8) -> int:
        """Pack the least-occupied replica's lanes onto the rest of
        the fleet (bounded by ``max_moves`` and the others' spare
        slots) so low-tide scale-down can actually reclaim a worker —
        the long-tail streams that used to pin a nearly-idle replica
        now migrate off it.  Returns the number of lanes moved."""
        if migration_killed():
            return 0
        usable = [r for r in self._replicas
                  if r.usable() and r.decode_capable()
                  and not r.driver.is_draining()]
        if len(usable) < 2:
            return 0
        with self._lock:
            by_rep: dict = {}
            for preq in self._requests.values():
                if preq.replica is not None:
                    by_rep.setdefault(preq.replica.idx,
                                      []).append(preq.handle.id)
        occupied = [r for r in usable if by_rep.get(r.idx)]
        if len(occupied) < 2:
            return 0
        donor = min(occupied, key=lambda r: (len(by_rep[r.idx]),
                                             -r.idx))
        spare = sum(max(0, r.slots - r.driver.active_slots())
                    for r in usable if r is not donor)
        moves = min(max_moves, len(by_rep[donor.idx]), spare)
        if moves <= 0:
            return 0
        moved = sum(self.migrate(rid)
                    for rid in by_rep[donor.idx][:moves])
        if moved:
            events.instant("pool/defragment", donor=donor.idx,
                           moved=moved)
        return moved

    def _on_chunk(self, preq: _PoolRequest, chunk: list) -> None:
        outer = preq.handle
        now = time.monotonic()
        m = self._metrics
        if not preq.queue_wait_seen:
            preq.queue_wait_seen = True
            granted = preq.inner.slot_granted_at or now
            if m is not None:
                m.queue_wait.observe(max(0.0, granted - outer.t_submit))
        if outer.first_token_at is None:
            outer.first_token_at = now
            if m is not None:
                m.ttft.observe(now - outer.t_submit)
        if m is not None:
            m.tokens.inc(len(chunk))
            if outer.last_commit_at is not None:
                m.inter_token.observe(
                    (now - outer.last_commit_at) / len(chunk))
        outer.last_commit_at = now
        # No pool-side commit instant: the replica's driver already
        # records request/commit for every chunk (with its replica id).
        outer._push_new(list(outer.prompt) + preq.generated)

    def _finish(self, preq: _PoolRequest, tokens: Optional[list],
                error: Optional[BaseException], status: str) -> None:
        outer = preq.handle
        with self._lock:
            if outer.id not in self._requests:
                return                      # already finished
            del self._requests[outer.id]
            self._terminal[outer.id] = status
            while len(self._terminal) > _TERMINAL_KEEP:
                self._terminal.popitem(last=False)
        m = self._metrics
        if m is not None:
            m.requests.inc(label_value=status)
            if status == "ok":
                m.latency.observe(time.monotonic() - outer.t_submit)
        events.instant("request/pool_retire", request_id=outer.id,
                       status=status, failovers=preq.failovers,
                       migrations=preq.migrations)
        outer._resolve(tokens, error)
        # A migrate() caller blocked on a request that just finished
        # must not hang out its timeout: signal failure (migrate_ok
        # stays whatever the relay last published — False unless the
        # move actually committed before the finish).
        ev = preq.migrate_done
        if ev is not None:
            ev.set()

    # -- request forensics / control ---------------------------------------

    def request_status(self, request_id: int) -> str:
        with self._lock:
            status = self._terminal.get(request_id)
            if status is not None:
                return status
            preq = self._requests.get(request_id)
        if preq is None:
            return "unknown"
        rep, inner = preq.replica, preq.inner
        if rep is None or inner is None:
            return "queued"                 # placing / failing over
        status = rep.driver.request_status(request_id)
        if status in ("queued", "active"):
            return status
        return "active"     # life just ended; the pump is resolving

    def abandon(self, handle: RequestHandle) -> None:
        """Streaming client went away: collapse the deadline so the
        current life is cancelled at the replica's next sweep and the
        pump expires instead of decoding for nobody."""
        handle.deadline = time.monotonic()
        with self._lock:
            preq = self._requests.get(handle.id)
        if preq is not None:
            rep, inner = preq.replica, preq.inner
            if rep is not None and inner is not None:
                rep.driver.abandon(inner)

    # -- health monitor ----------------------------------------------------

    @thread_role("watchdog")
    def _monitor(self) -> None:
        while not self._stop.wait(self._monitor_poll_s):
            for rep in self._replicas:
                if rep.dead:
                    continue
                drv = rep.driver
                reason = None
                failure = drv.failure()
                if failure is not None:
                    reason = f"driver failed: {failure!r}"
                elif not drv.alive() and (not drv.is_draining()
                                          or drv.vanished()):
                    # Drivers that can say HOW they vanished do (a
                    # ProcDriver reports the worker's wait status —
                    # "killed by signal 9" beats "vanished").  The
                    # drain exemption covers ONLY an orderly drain: a
                    # worker SIGKILLed/OOMed mid-drain vanishes
                    # abruptly (no BYE, nonzero wait status) and must
                    # be classified dead, not pruned as a clean
                    # scale-down.
                    how = getattr(drv, "vanish_reason", None)
                    reason = ((how() if how is not None else None)
                              or "driver vanished (no corpse, no drain)")
                elif (self._watchdog_s is not None
                      and drv.steps_completed() > 0
                      and drv.step_elapsed() > self._watchdog_s):
                    # Armed only after a completed step: the first
                    # dispatch compiles (XLA — minutes on a cold TPU)
                    # and must not read as a hang.
                    reason = (f"dispatch hung > {self._watchdog_s:g}s "
                              f"(watchdog)")
                if reason is not None:
                    self._declare_dead(rep, reason)

    def _declare_dead(self, rep: Replica, reason: str) -> None:
        rep.mark_dead(reason)
        # Fence the corpse: a wedged dispatch that WAKES later must
        # not drive the device (or consume armed chaos-fault budgets)
        # after its requests failed over — the driver loop exits at
        # its next iteration instead of dispatching.
        rep.driver.poison(reason)
        events.instant("replica/dead", replica=rep.idx, reason=reason)
        logger.error("replica %d declared DEAD: %s (%d alive)",
                     rep.idx, reason, self.alive_count())

    # -- drain -------------------------------------------------------------

    def drain(self) -> None:
        """Stop admitting new pool requests; already-accepted work
        (including failover re-admissions) runs to completion.
        Idempotent and non-blocking — ``join()`` does the staged
        per-replica drain."""
        with self._lock:
            self._draining = True

    def join(self, timeout: Optional[float] = None) -> bool:
        """Drain and wait: replicas drain ONE AT A TIME (capacity
        degrades gradually — the pool analog of the single driver's
        stop-the-world drain), then the surviving pumps finish.
        Returns True when everything drained inside ``timeout``."""
        self.drain()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)

        def left() -> Optional[float]:
            return (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))

        drained = True
        for rep in self._replicas:          # sequential, by design
            if not rep.usable():
                continue
            # Evacuate BEFORE the drain flag flips: live lanes migrate
            # to a survivor and keep decoding (drain cost becomes one
            # KV ship instead of waiting out the longest stream).
            # With one replica left — or TTD_NO_MIGRATION=1 — this is
            # a no-op and the drain waits for completion, the pre-
            # migration behavior byte-for-byte.
            self._evacuate(rep, left())
            rep.driver.drain()
            drained &= rep.driver.join(left())
        # Snapshot under the lock: pumps _finish() concurrently (del
        # under ``_lock``) and a dict-values iteration racing those
        # dels raises "dictionary changed size" in THIS thread.
        with self._lock:
            pending = list(self._requests.values())
        for preq in pending:
            t = preq.thread
            if t is not None:
                t.join(left())
                drained &= not t.is_alive()
        self._stop.set()
        with self._lock:
            drained &= not self._requests
        return drained
