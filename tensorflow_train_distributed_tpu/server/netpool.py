"""Multi-host serving: TCP worker daemons dial into the gateway's pool.

``server.procpool`` put every replica in a subprocess behind the frame
protocol — but still on the gateway's host, behind a ``socketpair``.
This module crosses the MACHINE boundary with the protocol unchanged:
the gateway opens one listening TCP socket (``NetPool``), and
standalone worker daemons (``tools/serve_worker``) dial in, send the
versioned ``HELLO`` (now carrying their disaggregated-serving
``role``), and become replicas.  The parent half of the frame loop is
``ProcDriver`` almost verbatim — ``NetDriver`` overrides only what was
process-shaped:

- **no spawn**: a replica exists because a worker dialed in; the
  acceptor thread wraps each accepted connection in a driver and
  publishes it to the pool (the scaler's atomic-snapshot idiom);
- **no corpse**: worker death is an EOF (or ECONNRESET) on the TCP
  stream — classified ``disconnected``, never consulted via waitpid;
  a clean drain still ends with ``BYE`` before the close, so orderly
  scale-down and abrupt death stay distinguishable;
- **poison closes the socket**: we cannot SIGKILL across hosts, but a
  closed socket guarantees a wedged worker that wakes later is never
  read again (its next write dies with EPIPE);
- **respawn is a re-dial**: the supervisor's restart-budget semantics
  survive the inversion of control — while the budget lasts, a fleet
  below ``scale_min`` keeps placement waiting (``NoReplicas`` becomes
  a bounded wait) and each replacement dial-in counts a restart; a
  crash-looping worker exhausts the budget and further re-dials are
  refused at accept.

Everything request-shaped — routing (now role-aware), KV-prefix
affinity, the hung-dispatch watchdog, resume-from-token failover, the
prefill→decode KV handoff — is inherited from ``ReplicaPool`` and
``ProcDriver`` untouched, so the gateway stays replica-blind while the
fleet spans machines.  ``TTD_NO_DISAGG=1`` collapses the role split
and handoff (``server.replicas.disagg_killed``); the transport itself
has no kill switch — it IS the deployment.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Optional

from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    concurrency_guarded,
    thread_role,
)
from tensorflow_train_distributed_tpu.server import proto
from tensorflow_train_distributed_tpu.server.procpool import (
    ProcDriver,
    RemoteEngine,
    WorkerSpec,
)
from tensorflow_train_distributed_tpu.server.replicas import (
    Replica,
    ReplicaPool,
)

logger = logging.getLogger(__name__)


@concurrency_guarded
class NetDriver(ProcDriver):
    """The ``EngineDriver`` surface over one dialed-in TCP worker.

    The frame machinery (reader loop, dispatch, submit, stats fold,
    handoff rendezvous, drain) is ProcDriver's — it only ever touches
    the socket pair and the sender, which this class points at the
    accepted connection.  Liveness is connection-shaped: alive until
    the stream fails or closes, vanished when it closed without the
    worker's ``BYE``.
    """

    # _closed is the connection's terminal flag: set by the reader at
    # EOF and by poison()/join() on the declaring thread — like the
    # base class's _vanished/_drained publishes, it only ever goes
    # False→True and every reader tolerates either order.

    def __init__(self, spec: WorkerSpec, engine: RemoteEngine,
                 sock: socket.socket, addr, *,
                 replica_id: Optional[int] = None, max_queue: int = 64,
                 default_timeout_s: Optional[float] = None,
                 retry_after_s: float = 1.0):
        super().__init__(spec, engine, replica_id=replica_id,
                         max_queue=max_queue,
                         default_timeout_s=default_timeout_s,
                         retry_after_s=retry_after_s)
        self._conn = sock
        self._addr = (f"{addr[0]}:{addr[1]}"
                      if isinstance(addr, tuple) else str(addr))
        self._closed = False

    def start(self) -> "NetDriver":
        sock = self._conn
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass                    # AF_UNIX test sockets have no TCP
        self._sock = sock
        self._rfp = sock.makefile("rb")
        self._wfp = sock.makefile("wb")
        self._sender = proto.FrameSender(self._wfp,
                                         self._spec.max_frame_bytes)
        self._stats_rx = time.monotonic()
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"net-reader-{self._replica_id}", daemon=True)
        self._reader.start()
        events.instant("replica/worker_dialin",
                       replica=self._replica_id, addr=self._addr)
        return self

    @property
    def addr(self) -> str:
        return self._addr

    # -- connection-shaped liveness (the proc overrides) -----------------

    def alive(self) -> bool:
        return self._failed is None and not self._closed

    def _corpse_rc(self) -> Optional[int]:
        return None                 # no corpse across a TCP boundary

    def _stream_error(self, e: BaseException) -> None:
        """A remote worker SIGKILLed/OOMed mid-write tears the TCP
        stream down as ECONNRESET — the death's symptom, exactly what
        EOF stands for, and there is no corpse to consult across
        hosts.  Anything else (an undecodable frame) stays a protocol
        failure on THIS replica."""
        if isinstance(e, OSError):
            self._on_eof()
            return
        self._fail_protocol(proto.ProtocolError(
            f"frame stream error: {type(e).__name__}: {e}"))

    def _on_eof(self) -> None:
        self._closed = True
        if not self._drained and self._failed is None:
            # No BYE before the close: SIGKILL semantics.  Nothing is
            # resolved here — the pool pump's liveness watch fails the
            # in-flight streams over, same as the subprocess EOF.
            self._vanished = True
            logger.warning("net worker %s (%s) disconnected without "
                           "BYE", self._replica_id, self._addr)
        self._fail_handoffs()
        self._corpse_snapshot(None)
        events.instant("replica/worker_eof", replica=self._replica_id,
                       addr=self._addr, drained=self._drained)

    def vanished(self) -> bool:
        return self._vanished

    def vanish_reason(self) -> Optional[str]:
        if not self.vanished():
            return None
        return f"worker at {self._addr} disconnected (no BYE)"

    def failure_class(self) -> Optional[str]:
        if isinstance(self._failed, proto.ProtocolError):
            return "protocol"
        if self._failed is not None:
            return "worker_error"
        if self.vanished():
            return "disconnected"
        return None

    def health_extra(self) -> dict:
        d = super().health_extra()
        d["addr"] = self._addr
        d["transport"] = "tcp"
        return d

    def poison(self, reason: str) -> None:
        """Fence a declared-dead remote worker: no cross-host SIGKILL
        exists, but closing the socket guarantees nothing it streams
        is ever read again — a wedged dispatch that wakes later must
        not commit into a request that already failed over."""
        self._poisoned = reason
        logger.warning("closing poisoned net worker %s (%s): %s",
                       self._replica_id, self._addr, reason)
        self._close_conn()

    def _close_conn(self) -> None:
        self._closed = True
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def join(self, timeout: Optional[float] = None) -> bool:
        """Drain and wait for the worker's BYE + close (the reader's
        exit): the worker finishes its backlog, says BYE, and closes;
        a worker that never does is abandoned at the timeout."""
        self.drain()
        r = self._reader
        if r is not None:
            r.join(timeout)
            if r.is_alive():
                return False
        self._close_conn()
        return True


class _NetReplica(Replica):
    """One dialed-in worker: the base Replica with a NetDriver and the
    parent-side facade in the engine seat."""

    def __init__(self, idx: int, spec: WorkerSpec,
                 sock: socket.socket, addr, *, max_queue: int,
                 default_timeout_s: Optional[float],
                 retry_after_s: float):
        engine = RemoteEngine()
        driver = NetDriver(spec, engine, sock, addr, replica_id=idx,
                           max_queue=max_queue,
                           default_timeout_s=default_timeout_s,
                           retry_after_s=retry_after_s)
        super().__init__(idx, engine, max_queue=max_queue,
                         default_timeout_s=default_timeout_s,
                         retry_after_s=retry_after_s, driver=driver)


@concurrency_guarded
class NetPool(ReplicaPool):
    """``ReplicaPool`` over TCP dial-in workers.

    The pool starts EMPTY and grows as workers dial in; ``wait_ready``
    blocks until ``scale_min`` of them finished their HELLO (engine
    built + warm on the worker's host).  Worker lifecycle is inverted
    relative to the subprocess pool — the pool cannot spawn what it
    does not own — so the supervisor idiom becomes: dead replicas stay
    listed for forensics, placement WAITS while the re-dial budget
    lasts (``_placement_may_recover``), and each dial-in that replaces
    dead capacity counts against ``max_restarts``; once the budget is
    spent, further re-dials are refused at accept (a crash-looping
    remote worker must not flap the fleet forever).  Dial-ins beyond
    ``max_workers`` usable replicas are refused outright.
    """

    # Acceptor-thread-owned bookkeeping (single writer; monitor and
    # handler threads read atomic scalars).  The lock-guarded request
    # structures are declared on ReplicaPool itself.
    _GUARDED_BY = {
        "_replicas": (None, "acceptor", "main"),
        "_next_idx": (None, "acceptor"),
        "_accepted": (None, "acceptor"),
        "_restarts": (None, "acceptor"),
    }

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 scale_min: int = 1, max_workers: int = 16,
                 max_frame_bytes: int = proto.MAX_FRAME_BYTES,
                 stats_interval_s: float = 0.2,
                 max_queue: int = 64, validate=None,
                 default_timeout_s: Optional[float] = None,
                 retry_after_s: float = 1.0,
                 watchdog_timeout_s: Optional[float] = 30.0,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 1.0,
                 replica_max_queue: Optional[int] = None,
                 monitor_poll_s: Optional[float] = None,
                 max_restarts: int = 8):
        if not 1 <= scale_min <= max_workers:
            raise ValueError(
                f"need 1 <= scale_min ({scale_min}) <= max_workers "
                f"({max_workers})")
        # The spec carries only the frame-protocol knobs here (frame
        # bound, heartbeat cadence for the watchdog feed): engine
        # construction happens on the worker's host, from ITS flags.
        self._spec = WorkerSpec(max_frame_bytes=max_frame_bytes,
                                stats_interval_s=stats_interval_s)
        self._host = host
        self._cfg_port = int(port)
        self._scale_min = scale_min
        self._max_workers = max_workers
        self._max_restarts = max_restarts
        self._restarts = 0
        self._accepted = 0
        self._next_idx = 0
        self._budget_logged = False
        self._listener: Optional[socket.socket] = None
        self._port: Optional[int] = None
        self._allow_empty = True        # replicas dial in after start
        super().__init__([], max_queue=max_queue, validate=validate,
                         default_timeout_s=default_timeout_s,
                         retry_after_s=retry_after_s,
                         watchdog_timeout_s=watchdog_timeout_s,
                         backoff_base_s=backoff_base_s,
                         backoff_cap_s=backoff_cap_s,
                         replica_max_queue=replica_max_queue,
                         monitor_poll_s=monitor_poll_s)
        self._acceptor_thread = threading.Thread(
            target=self._accept_loop, name="net-acceptor", daemon=True)

    def _make_replica(self, idx: int, engine) -> Replica:
        raise NotImplementedError(
            "NetPool replicas dial in; nothing to make")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "NetPool":
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((self._host, self._cfg_port))
        lsock.listen(16)
        self._listener = lsock
        self._port = lsock.getsockname()[1]
        super().start()
        self._acceptor_thread.start()
        logger.info("net pool listening on %s:%d (scale_min=%d, "
                    "max_workers=%d)", self._host, self._port,
                    self._scale_min, self._max_workers)
        return self

    @property
    def port(self) -> int:
        """The bound listener port (live after ``start()``; with
        ``port=0`` the OS picked it — tests and launchers advertise
        this to workers)."""
        if self._port is None:
            raise RuntimeError("NetPool not started")
        return self._port

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until ``scale_min`` dialed-in workers finished their
        HELLO and are still usable — the launcher gate before
        advertising the HTTP port."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            ready = sum(1 for rep in self._replicas
                        if rep.usable() and rep.driver.ready())
            if ready >= self._scale_min:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def restarts_total(self) -> int:
        return self._restarts

    def degraded(self) -> bool:
        """Reduced capacity means fewer usable workers than the
        ``scale_min`` floor — corpses kept for /healthz forensics do
        not count against a fleet re-dialed back to strength."""
        return self.alive_count() < self._scale_min

    def _restart_budget_left(self) -> bool:
        return self._restarts < self._max_restarts

    def _placement_may_recover(self) -> bool:
        """A thin fleet recovers when a worker re-dials: placement
        waits (bounded by each request's own deadline) while the
        listener is up and the re-dial budget lasts."""
        return (not self.is_draining() and self._listener is not None
                and self._restart_budget_left())

    # -- the acceptor ----------------------------------------------------

    @thread_role("acceptor")
    def _accept_loop(self) -> None:
        lsock = self._listener      # join() nulls the attribute; the
        while True:                 # socket object itself stays valid
            try:                    # (accept raises once it closes)
                conn, addr = lsock.accept()
            except OSError:
                return              # listener closed: shutting down
            if self._stop.is_set():
                conn.close()
                return
            if self.is_draining():
                conn.close()        # no new capacity mid-drain
                continue
            try:
                self._admit(conn, addr)
            except Exception:   # noqa: BLE001 — acceptor must survive
                logger.exception("failed to admit dial-in from %s",
                                 addr)
                conn.close()

    def _admit(self, conn: socket.socket, addr) -> None:
        usable = self.alive_count()
        if usable >= self._max_workers:
            logger.warning("refusing dial-in from %s: fleet full "
                           "(%d usable)", addr, usable)
            conn.close()
            return
        # A dial-in that REPLACES dead capacity (the fleet already
        # reached scale_min once, and is now below it) is a respawn in
        # supervisor terms: counted, budgeted.  Initial fleet formation
        # and scale-out beyond the floor are free.
        respawn = (self._accepted >= self._scale_min
                   and usable < self._scale_min)
        if respawn and not self._restart_budget_left():
            if not self._budget_logged:
                self._budget_logged = True
                events.instant("replica/restart_budget_exhausted",
                               restarts=self._restarts)
                logger.error(
                    "re-dial budget exhausted after %d replacement "
                    "dial-ins; refusing new workers", self._restarts)
            conn.close()
            return
        if respawn:
            self._restarts += 1
            counter = getattr(self._metrics, "replica_restarts", None)
            if counter is not None:
                counter.inc()
        self._accepted += 1
        idx = self._next_idx
        self._next_idx += 1
        rep = _NetReplica(idx, self._spec, conn, addr,
                          max_queue=self._replica_max_queue,
                          default_timeout_s=self._default_timeout_s,
                          retry_after_s=self._retry_after_s)
        rep.driver.start()
        # Publish AFTER start: readers must never see a replica whose
        # driver has no reader thread yet (the scaler's rule).
        self._replicas = self._replicas + [rep]
        events.instant("replica/dialin", replica=idx,
                       addr=rep.driver.addr, respawn=respawn)
        logger.info("worker dialed in from %s -> replica %d "
                    "(fleet=%d%s)", rep.driver.addr, idx,
                    len(self._replicas),
                    ", respawn" if respawn else "")

    # -- drain -----------------------------------------------------------

    def join(self, timeout: Optional[float] = None) -> bool:
        drained = super().join(timeout)
        lsock, self._listener = self._listener, None
        if lsock is not None:
            try:
                lsock.close()       # unblocks the acceptor's accept()
            except OSError:
                pass
        if self._acceptor_thread.is_alive():
            self._acceptor_thread.join(timeout=5.0)
        return drained
